"""Chaos round-trips: preemption-safe training must be BIT-exact.

The contract (ISSUE 8 / ROADMAP production posture): SIGKILL a training
subprocess at a (seeded-)random iteration, restart with resume=auto, and
the final model TEXT is byte-identical to the uninterrupted run —
across plain/bagged/DART/multiclass and a real 2-process
tree_learner=data run.  Corrupt snapshots (truncated / bit-flipped /
zero-length) are skipped with a warning naming the file and the reason,
resuming from the previous valid one.  The snapshot cadence itself adds
ZERO recompiles at steady state (xla_guard), and every named faultpoint
is reachable through its real seam.

Subprocess round-trips are marked `slow` (scripts/chaos_smoke.sh runs
the same round-trip as a fast smoke); the in-process coverage and
compile-budget tests ride tier-1.
"""

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.models.gbdt import create_boosting
from lightgbm_tpu.objectives import create_objective
from lightgbm_tpu.resilience import faults
from lightgbm_tpu.resilience.snapshot import SnapshotManager

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SIGKILLED = (-signal.SIGKILL, 137)


@pytest.fixture(autouse=True)
def _clean_fault_registry():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# single-host kill-resume round-trips (subprocess CLI, slow tier)
# ---------------------------------------------------------------------------

def _write_data(tmp_path, objective):
    rng = np.random.RandomState(3)
    n = 400
    x = rng.randn(n, 6)
    signal_ = x[:, 0] + 0.5 * x[:, 1] * x[:, 2]
    if objective == "multiclass":
        edges = np.quantile(signal_, [1 / 3, 2 / 3])
        y = np.digitize(signal_, edges)
    else:
        y = (signal_ > 0).astype(int)
    p = str(tmp_path / ("train_%s.tsv" % objective))
    with open(p, "w") as f:
        for i in range(n):
            f.write("%d\t" % y[i]
                    + "\t".join("%.6g" % v for v in x[i]) + "\n")
    return p


def _run_cli(args, faults_spec=None, check=True):
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "LGBM_TPU_FAULTS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    if faults_spec:
        env["LGBM_TPU_FAULTS"] = faults_spec
    proc = subprocess.run(
        [sys.executable, "-m", "lightgbm_tpu"] + args,
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        timeout=600)
    out = proc.stdout.decode()
    if check:
        assert proc.returncode == 0, out
    return proc.returncode, out


CHAOS_CONFIGS = {
    "binary": {"objective": "binary"},
    "multiclass": {"objective": "multiclass", "num_class": "3"},
    "dart": {"objective": "binary", "boosting": "dart",
             "drop_rate": "0.3"},
    # period=3 vs bagging_freq=2: snapshots land both ON a re-bagging
    # boundary (iteration 6) and mid-epoch (3, 9), so resume crosses a
    # re-bag inside the recovered window
    "bagged": {"objective": "binary", "bagging_fraction": "0.5",
               "bagging_freq": "2"},
}

#: seeded kill iterations, drawn once (np.random.RandomState(8)
#: .randint(5, 18, 4)) and PINNED so failures reproduce exactly
KILL_AT = {"binary": 7, "multiclass": 13, "dart": 10, "bagged": 16}


def _base_args(data, model, extra):
    args = ["task=train", "data=" + data, "output_model=" + model,
            "num_iterations=20", "num_leaves=7", "max_bin=63",
            "min_data_in_leaf=20", "metric=", "verbose=1"]
    return args + ["%s=%s" % kv for kv in extra.items()]


@pytest.mark.slow
@pytest.mark.parametrize("name", sorted(CHAOS_CONFIGS))
def test_kill_resume_is_byte_exact(tmp_path, name):
    extra = CHAOS_CONFIGS[name]
    data = _write_data(tmp_path, extra["objective"])
    base = str(tmp_path / "base.txt")
    _run_cli(_base_args(data, base, extra))

    chaos = str(tmp_path / "chaos.txt")
    snaps = str(tmp_path / "snaps")
    chaos_args = _base_args(data, chaos, extra) + [
        "snapshot_period=3", "snapshot_dir=" + snaps, "resume=auto"]
    # the flush faultpoint fires once per iteration dispatch on this
    # CPU config (iter_batch=1), so hit N == "mid-iteration N"
    rc, out = _run_cli(
        chaos_args,
        faults_spec="flush.device_get@%d=kill" % KILL_AT[name],
        check=False)
    assert rc in SIGKILLED, "expected the injected SIGKILL:\n" + out
    assert not os.path.exists(chaos), \
        "a killed run must never commit a (truncated) model file"

    rc, out = _run_cli(chaos_args)
    assert "Resumed from snapshot" in out
    assert open(base, "rb").read() == open(chaos, "rb").read(), \
        "resume=auto diverged from the uninterrupted run (%s)" % name


@pytest.mark.slow
def test_sigterm_flushes_final_snapshot(tmp_path):
    """Graceful preemption: SIGTERM mid-run writes a snapshot at the
    next segment boundary and exits 0; resume completes bit-exact."""
    import threading
    import time

    data = _write_data(tmp_path, "binary")
    base = str(tmp_path / "base.txt")
    _run_cli(_base_args(data, base, {"objective": "binary"}))

    out_model = str(tmp_path / "chaos.txt")
    snaps = str(tmp_path / "snaps")
    args = _base_args(data, out_model, {"objective": "binary"}) + [
        "snapshot_period=5", "snapshot_dir=" + snaps, "resume=auto"]
    env = {k: v for k, v in os.environ.items() if k != "XLA_FLAGS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, "-m", "lightgbm_tpu"] + args, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT)

    # SIGTERM once the training loop is demonstrably under way
    def _term():
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if os.path.isdir(snaps) and os.listdir(snaps):
                break
            time.sleep(0.05)
        proc.send_signal(signal.SIGTERM)

    t = threading.Thread(target=_term)
    t.start()
    out = proc.communicate(timeout=600)[0].decode()
    t.join()
    assert proc.returncode == 0, out
    assert "Preempted at iteration" in out
    assert os.listdir(snaps), "no snapshot flushed on SIGTERM"

    rc, out = _run_cli(args)
    assert "Resumed from snapshot" in out
    assert open(base, "rb").read() == open(out_model, "rb").read()


@pytest.mark.slow
def test_corrupt_snapshots_skipped_end_to_end(tmp_path):
    """Damage the two NEWEST snapshots two different ways: resume=auto
    names both rejected files (with the reason), falls back to the
    previous valid one, and still finishes byte-exact."""
    data = _write_data(tmp_path, "binary")
    base = str(tmp_path / "base.txt")
    _run_cli(_base_args(data, base, {"objective": "binary"}))

    chaos = str(tmp_path / "chaos.txt")
    snaps = str(tmp_path / "snaps")
    chaos_args = _base_args(data, chaos, {"objective": "binary"}) + [
        "snapshot_period=3", "snapshot_dir=" + snaps, "resume=auto"]
    rc, out = _run_cli(chaos_args,
                       faults_spec="flush.device_get@11=kill",
                       check=False)
    assert rc in SIGKILLED, out
    names = sorted(os.listdir(snaps))      # iterations 3, 6, 9
    assert len(names) == 3, names
    newest = os.path.join(snaps, names[-1])
    second = os.path.join(snaps, names[-2])
    raw = open(newest, "rb").read()
    with open(newest, "wb") as f:          # truncate
        f.write(raw[:len(raw) // 2])
    raw = bytearray(open(second, "rb").read())
    raw[len(raw) // 2] ^= 0x04             # bit flip
    with open(second, "wb") as f:
        f.write(bytes(raw))

    rc, out = _run_cli(chaos_args)
    assert ("Skipping snapshot %s" % newest) in out
    assert ("Skipping snapshot %s" % second) in out
    assert out.count("corrupt") >= 2       # each rejection names why
    assert ("Resumed from snapshot %s" % os.path.join(snaps, names[0])) \
        in out
    assert open(base, "rb").read() == open(chaos, "rb").read()


# ---------------------------------------------------------------------------
# 2-process tree_learner=data kill-resume (slow tier)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_multihost_kill_resume_two_process(tmp_path):
    """Whole-pool preemption under tree_learner=data: both ranks die at
    the same injected checkpoint.commit, restart with resume=auto,
    agree on the common snapshot iteration via the rank-sync
    allgather, and finish byte-identical to the uninterrupted run."""
    import socket as socketlib

    rng = np.random.RandomState(0)
    n, ncol = 800, 5
    x = rng.randn(n, ncol)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int)
    data = tmp_path / "train.tsv"
    data.write_text("\n".join(
        "\t".join([str(y[i])] + ["%f" % v for v in x[i]])
        for i in range(n)) + "\n")
    worker = os.path.join(os.path.dirname(__file__), "mh_chaos_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "LGBM_TPU_FAULTS")}
    snaps = str(tmp_path / "snaps")

    def run_phase(phase, faults_spec="", expect_kill=False):
        s = socketlib.socket()
        s.bind(("localhost", 0))
        port = str(s.getsockname()[1])
        s.close()
        procs = [subprocess.Popen(
            [sys.executable, worker, str(r), "2", port, str(data),
             str(tmp_path / ("model_%s_%d.txt" % (phase, r))),
             snaps, phase, faults_spec],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
            for r in range(2)]
        logs = [p.communicate(timeout=600)[0].decode() for p in procs]
        for r, p in enumerate(procs):
            if expect_kill:
                assert p.returncode in SIGKILLED, \
                    "rank %d should have been SIGKILLed:\n%s" \
                    % (r, logs[r])
            else:
                assert p.returncode == 0, \
                    "rank %d failed:\n%s" % (r, logs[r])
        return logs

    run_phase("base")
    # both ranks SIGKILL the instant their SECOND snapshot (iteration
    # 6) is durable — a whole-pool preemption mid-run
    run_phase("kill", faults_spec="checkpoint.commit@2=kill",
              expect_kill=True)
    logs = run_phase("resume")
    for r in range(2):
        assert "resumed_at=6" in logs[r], logs[r]
        base_m = open(str(tmp_path / ("model_base_%d.txt" % r)),
                      "rb").read()
        res_m = open(str(tmp_path / ("model_resume_%d.txt" % r)),
                     "rb").read()
        assert base_m == res_m, "rank %d resume diverged" % r
    assert open(str(tmp_path / "model_resume_0.txt"), "rb").read() \
        == open(str(tmp_path / "model_resume_1.txt"), "rb").read()


# ---------------------------------------------------------------------------
# snapshot cadence adds zero recompiles (tier-1)
# ---------------------------------------------------------------------------

def _booster(extra=None):
    rng = np.random.RandomState(1)
    n = 400
    x = rng.randn(n, 6).astype(np.float32)
    y = (x[:, 0] + 0.5 * x[:, 1] * x[:, 2] > 0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
              "min_data_in_leaf": 5, "metric": "", **(extra or {})}
    ds = lgb.Dataset(x, label=y,
                     params={k: str(v) for k, v in params.items()})
    cfg = Config.from_params({k: str(v) for k, v in params.items()})
    obj = create_objective(cfg)
    obj.init(ds.inner.metadata, ds.inner.num_data)
    return create_boosting(cfg, ds.inner, obj)


def test_snapshot_cadence_zero_recompiles(tmp_path, xla_guard):
    """Crossing snapshot boundaries at steady state compiles NOTHING:
    the cadenced save_checkpoint flush reuses the warm executables."""
    booster = _booster()
    mgr = SnapshotManager(str(tmp_path), period=2, resume="off")
    for _ in range(4):                    # warm: spans snapshots @2, @4
        booster.train_one_iter(None, None, False)
        if mgr.due(booster.iter):
            mgr.write(booster)
    with xla_guard(0, what="snapshot cadence at steady state"):
        for _ in range(4):                # crosses snapshots @6, @8
            booster.train_one_iter(None, None, False)
            if mgr.due(booster.iter):
                mgr.write(booster)
    assert len(os.listdir(str(tmp_path))) == 4


def test_snapshot_resume_matches_straight_run(tmp_path):
    """In-process api.train honors snapshot_period/resume: a booster
    restored via resume=auto finishes bit-identical to the oracle."""
    rng = np.random.RandomState(5)
    x = rng.randn(300, 5)
    y = (x[:, 0] > 0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5, "metric": ""}
    oracle = lgb.train(params, lgb.Dataset(x, label=y),
                       num_boost_round=8, verbose_eval=False)

    snaps = str(tmp_path / "s")
    p2 = {**params, "snapshot_period": 3, "snapshot_dir": snaps}
    lgb.train(p2, lgb.Dataset(x, label=y), num_boost_round=5,
              verbose_eval=False)        # stops at 5; snapshot at 3
    assert os.listdir(snaps)
    resumed = lgb.train({**p2, "resume": "auto"},
                        lgb.Dataset(x, label=y), num_boost_round=8,
                        verbose_eval=False)
    assert resumed._gbdt.iter == 8
    assert oracle.model_to_string() == resumed.model_to_string()


def test_resume_rejects_changed_config(tmp_path, capsys):
    """Snapshots are bound to the config/dataset that wrote them:
    resume=auto under changed hyper-parameters skips them as stale
    (fresh start, bit-identical to a never-snapshotted run), and an
    explicit resume=<path> refuses outright."""
    from lightgbm_tpu.utils import log

    rng = np.random.RandomState(6)
    x = rng.randn(300, 5)
    y = (x[:, 0] > 0).astype(np.float32)
    snaps = str(tmp_path / "s")
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5, "metric": "",
              "snapshot_period": 3, "snapshot_dir": snaps}
    lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=5,
              verbose_eval=False)            # snapshot at iteration 3
    snap_files = os.listdir(snaps)
    assert snap_files

    # resume-only manager (period 0): the changed-config run must not
    # overwrite the 7-leaf snapshot the test is about
    changed = {**params, "num_leaves": 15, "resume": "auto",
               "snapshot_period": 0}
    # the oracle must not touch snapshot_dir: it would overwrite the
    # 7-leaf snapshot with a 15-leaf one the resumed run could then use
    oracle = lgb.train({k: v for k, v in changed.items()
                        if k not in ("resume", "snapshot_period",
                                     "snapshot_dir")},
                       lgb.Dataset(x, label=y), num_boost_round=4,
                       verbose_eval=False)
    capsys.readouterr()                      # drop pre-test output
    fresh = lgb.train(changed, lgb.Dataset(x, label=y),
                      num_boost_round=4, verbose_eval=False)
    out = capsys.readouterr().out
    assert "Skipping snapshot" in out and "stale" in out
    assert "num_leaves" in out               # the moved key is named
    assert oracle.model_to_string() == fresh.model_to_string()

    explicit = {**params, "num_leaves": 15,
                "resume": os.path.join(snaps, sorted(snap_files)[0])}
    with pytest.raises(log.LightGBMError, match="rejected.*stale"):
        lgb.train(explicit, lgb.Dataset(x, label=y),
                  num_boost_round=4, verbose_eval=False)


def test_resume_honors_lowered_round_count(tmp_path):
    """Re-capping a run IS the legitimate config change resume permits
    — but the model must then hold exactly the requested rounds: a
    snapshot past num_boost_round is skipped and the next one at or
    below the cap is restored, bit-identical to a straight short run."""
    rng = np.random.RandomState(8)
    x = rng.randn(300, 5)
    y = (x[:, 0] > 0).astype(np.float32)
    snaps = str(tmp_path / "s")
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5, "metric": ""}
    lgb.train({**params, "snapshot_period": 1, "snapshot_dir": snaps},
              lgb.Dataset(x, label=y), num_boost_round=5,
              verbose_eval=False)          # snapshots at 1..5
    oracle = lgb.train(params, lgb.Dataset(x, label=y),
                       num_boost_round=3, verbose_eval=False)
    short = lgb.train({**params, "snapshot_period": 0,
                       "snapshot_dir": snaps, "resume": "auto"},
                      lgb.Dataset(x, label=y), num_boost_round=3,
                      verbose_eval=False)  # 4, 5 skipped; resumes at 3
    assert short._gbdt.iter == 3
    assert oracle.model_to_string() == short.model_to_string()


def test_api_params_arm_fault_schedule(tmp_path):
    """The `faults` config key injects through api.train too, not only
    the CLI — API-driven chaos tests must not pass vacuously."""
    rng = np.random.RandomState(7)
    x = rng.randn(200, 4)
    y = (x[:, 0] > 0).astype(np.float32)
    params = {"objective": "binary", "num_leaves": 7,
              "min_data_in_leaf": 5, "metric": "",
              "snapshot_period": 1, "snapshot_dir": str(tmp_path / "s"),
              "faults": "checkpoint.write@1=raise"}
    with pytest.raises(faults.FaultInjected):
        lgb.train(params, lgb.Dataset(x, label=y), num_boost_round=2,
                  verbose_eval=False)
    assert faults.fired("checkpoint.write") == 1


# ---------------------------------------------------------------------------
# out-of-core ingest: SIGKILL mid-ingest, resume to byte-identity
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_ingest_sigkill_resume_byte_identical(tmp_path):
    """SIGKILL the CLI ingest at the ingest.shard_write seam; the
    resumed run completes a shard directory whose shard payloads,
    metas and manifest are byte-identical to an uninterrupted
    ingest's."""
    data = _write_data(tmp_path, "binary")
    args = ["task=ingest", "data=" + data, "ingest_workers=1",
            "ingest_shard_rows=64", "ingest_memory_budget_mb=64"]
    clean = str(tmp_path / "clean")
    _run_cli(args + ["ingest_dir=" + clean])
    killed = str(tmp_path / "killed")
    out = _run_cli(args + ["ingest_dir=" + killed],
                   faults_spec="ingest.shard_write@3=kill", check=False)
    assert out[0] in SIGKILLED, out
    assert not os.path.exists(os.path.join(killed, "manifest.json"))
    out2 = _run_cli(args + ["ingest_dir=" + killed])
    assert "Resuming killed ingest" in out2[1]
    names = sorted(n for n in os.listdir(clean)
                   if n.startswith("shard_") or n == "manifest.json")
    assert names == sorted(n for n in os.listdir(killed)
                           if n.startswith("shard_")
                           or n == "manifest.json")
    assert len([n for n in names if n.endswith(".bins")]) >= 5
    for n in names:
        with open(os.path.join(clean, n), "rb") as fa, \
                open(os.path.join(killed, n), "rb") as fb:
            assert fa.read() == fb.read(), n


# ---------------------------------------------------------------------------
# every faultpoint is reachable through its REAL seam (tier-1)
# ---------------------------------------------------------------------------

def test_every_faultpoint_reachable(tmp_path, monkeypatch):
    """Drive each registered faultpoint through the production code
    path that hosts it and prove the seam was crossed (hits > 0) —
    the closed registry plus this test means a chaos schedule can
    target every seam and none is dead wiring."""
    from lightgbm_tpu.parallel import dist
    from lightgbm_tpu.resilience import net
    from lightgbm_tpu.resilience.atomic import write_npz

    class _Snap:
        iter = 4

        def save_checkpoint(self, path):
            write_npz(path, {"iter": np.int64(4),
                             "num_trees": np.int64(4),
                             "scores": np.zeros(2)})

    # checkpoint.write / checkpoint.commit
    SnapshotManager(str(tmp_path), 2, "off").write(_Snap())
    # flush.device_get: one real training dispatch + flush
    b = _booster()
    b.train_one_iter(None, None, False)
    b._flush_pending()
    # dist.connect
    net.connect_with_retry(lambda: None, "probe", deadline_s=5.0)
    # dist.send / dist.recv (single-process allgather is still the
    # real transport entry)
    out = dist.process_allgather(np.array([7], dtype=np.int64))
    assert out.reshape(-1)[0] == 7
    # serve.dispatch: a device-engine forest answering a predict
    from test_predict_fast import BINARY_MODEL
    from lightgbm_tpu.serving.forest import ServingForest
    forest = ServingForest(BINARY_MODEL, backend="jax")
    forest.predict(np.zeros((2, forest.max_feature_idx + 1)), "raw")
    # reload.parse: the serving hot-swap entry
    from lightgbm_tpu.serving.server import ServingState
    model_path = str(tmp_path / "m.txt")
    with open(model_path, "w") as f:
        f.write(BINARY_MODEL)
    cfg = Config.from_params({"task": "serve",
                              "input_model": model_path,
                              "serve_backend": "native"})
    state = ServingState(cfg, ServingForest(BINARY_MODEL,
                                            backend="native"))
    try:
        state.reload(model_path)
    finally:
        state.batcher.shutdown()
    # frontend.spawn: the multi-process front-end's worker (re)spawn —
    # the real seam is Frontend._spawn; two real subprocess workers
    # come up (native backend keeps them jax-free and fast) and drain
    from lightgbm_tpu.serving.frontend import Frontend
    fe_cfg = Config.from_params({"task": "serve",
                                 "input_model": model_path,
                                 "serve_port": "0",
                                 "serve_workers": "2",
                                 "serve_backend": "native"})
    fe = Frontend(fe_cfg)
    fe.start()
    try:
        assert len(fe.worker_pids()) == 2
    finally:
        fe.shutdown(drain_timeout=20.0)

    # ingest.shard_write: a real (tiny) out-of-core ingest
    from lightgbm_tpu.ingest.writer import ingest as run_ingest
    ing_src = _write_data(tmp_path, "binary")
    run_ingest([ing_src], str(tmp_path / "ingest_shards"),
               Config.from_params({"ingest_workers": "1",
                                   "ingest_shard_rows": "128"}))

    # refresh.train_spawn / refresh.eval / deploy.push /
    # deploy.promote: ONE real refresh-agent cycle against a native
    # serving fleet — the retrain subprocess is a trivial interpreter
    # (the spawn seam still crosses for real), and a winning
    # challenger drives push, shadow eval AND promotion
    from test_refresh import CHALLENGER_MODEL, WIN_EVAL
    from test_serving import serve as serve_ctx
    from lightgbm_tpu.ingest.manifest import snapshot_sources
    from lightgbm_tpu.refresh.agent import RefreshAgent

    champ = str(tmp_path / "refresh_champ.txt")
    with open(champ, "w") as f:
        f.write(BINARY_MODEL)
    evf = str(tmp_path / "refresh_eval.tsv")
    with open(evf, "w") as f:
        f.write(WIN_EVAL)
    dropd = tmp_path / "refresh_drop"
    dropd.mkdir()
    with open(str(dropd / "d.tsv"), "w") as f:
        f.write(WIN_EVAL)

    def _argv(self, data_path, out_model):
        return [sys.executable, "-c",
                "import pathlib, sys; "
                "pathlib.Path(sys.argv[1]).write_text(sys.argv[2])",
                out_model, CHALLENGER_MODEL]

    monkeypatch.setattr(RefreshAgent, "_train_argv", _argv)
    with serve_ctx(champ, serve_backend="native") as srv:
        agent = RefreshAgent(Config.from_params({
            "task": "refresh", "objective": "binary",
            "refresh_drop_dir": str(dropd),
            "refresh_serve_url": srv.url,
            "refresh_eval_data": evf, "input_model": champ,
            "refresh_deadline_s": "30"}))
        assert agent.run_cycle(snapshot_sources(str(dropd))) \
            == "promoted"

    missing = [n for n in faults.KNOWN_FAULTPOINTS
               if faults.hits(n) == 0]
    assert not missing, "faultpoints never reached: %s" % missing
