"""Worker for the multi-host RANK fused test
(test_parallel.py::test_multihost_rank_fused_matches_general).

Usage: python mh_rank_worker.py <rank> <nproc> <port> <data> <out> <mode>

mode=fused trains lambdarank through the query-sharded fused shard_map
step over the cross-process mesh (each process's lottery shard holds
whole queries; its gradient state is per-shard [Q, Lmax] blocks with
shard-local doc indices), with a transfer audit proving steady
iterations upload NOTHING O(rows) — per-iteration host traffic is the
O(packed tree) pull only.  mode=general forces the per-tree host-loop
path the fused step replaced (same device gradient impl, so models must
match byte-for-byte under hist_dtype=float64).
"""

import os
import sys

rank, nproc, port, data, out, mode = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
try:
    # cross-process collectives on the CPU backend need the gloo
    # implementation (without it the compiler rejects multiprocess
    # computations outright on CPU-only boxes)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
jax.distributed.initialize(coordinator_address="localhost:" + port,
                           num_processes=nproc, process_id=rank)

from lightgbm_tpu.config import Config  # noqa: E402
from lightgbm_tpu.io.dataset import load_dataset  # noqa: E402
from lightgbm_tpu.models.gbdt import create_boosting  # noqa: E402
from lightgbm_tpu.objectives import create_objective  # noqa: E402

cfg = Config.from_params({
    "objective": "lambdarank", "tree_learner": "data", "num_leaves": "8",
    "min_data_in_leaf": "5", "min_sum_hessian_in_leaf": "1",
    "hist_dtype": "float64", "metric": "", "is_save_binary_file": "false"})
ds = load_dataset(data, cfg, rank=rank, num_shards=nproc)
obj = create_objective(cfg)
obj.init(ds.metadata, ds.num_data)
if mode == "general":
    # the pre-fusion path: per-tree host gradients + shard_rows uploads
    # (same device gradient impl — the bit-parity oracle for the fused
    # query-sharded step)
    obj.row_shardable = False
booster = create_boosting(cfg, ds, obj)
if mode == "fused":
    assert booster._mh_fused and booster._can_fuse(), \
        "multi-host rank must take the fused query-sharded path"
    assert booster._layout_active and booster._shard_layout is not None
else:
    assert not booster._mh_fused and not booster._can_fuse()
booster.train_one_iter(None, None, False)
if mode == "fused":
    # transfer audit: the first iteration assembled the global scores /
    # bins / query-sharded gradient state; steady iterations must upload
    # nothing O(rows) — the general path pays two O(N_local) shard_rows
    # round trips (grad + hess) per tree
    uploads = []
    _orig_sr = booster.grower.shard_rows
    _orig_ps = booster.grower.put_spec
    booster.grower.shard_rows = lambda *a, **k: (
        uploads.append(("shard_rows", a[0].shape)), _orig_sr(*a, **k))[1]
    booster.grower.put_spec = lambda *a, **k: (
        uploads.append(("put_spec", a[0].shape)), _orig_ps(*a, **k))[1]
    for _ in range(2):
        booster.train_one_iter(None, None, False)
    booster.grower.shard_rows = _orig_sr
    booster.grower.put_spec = _orig_ps
    assert not uploads, \
        "steady fused rank iterations re-uploaded per-row state: %r" \
        % uploads
else:
    for _ in range(2):
        booster.train_one_iter(None, None, False)
booster.save_model_to_file(-1, True, out)
print("worker %d done (%s): %d trees" % (rank, mode,
                                         len(booster.models)))
