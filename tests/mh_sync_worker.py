"""Worker for the 2-process collective-trace test
(test_graftsync.py::test_two_process_traces_identical_and_statically_predicted).

Usage: python mh_sync_worker.py <rank> <nproc> <port> <data> <trace_out>
       <snap_dir>

Each worker joins the jax distributed runtime, then runs the REAL
multi-host paths under dist.trace_collectives(): dataset load (cache
vote + distributed bin finding), booster init (pad-length agreement),
snapshot resume agreement, a short tree_learner=data training with the
early-stop sync hook wired exactly as cli.init_train wires it, and a
preemption sync_flag.  The trace dumps to JSON for the parent to
compare across ranks and against graftsync's static model.
"""

import json
import os
import sys

rank, nproc, port, data, trace_out, snap_dir = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6])
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)
try:
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
jax.distributed.initialize(coordinator_address="localhost:" + port,
                           num_processes=nproc, process_id=rank)
assert jax.device_count() == 4 * nproc, jax.devices()

from lightgbm_tpu.config import Config  # noqa: E402
from lightgbm_tpu.parallel.dist import (trace_collectives,  # noqa: E402
                                        vote_any)
from lightgbm_tpu.resilience.snapshot import SnapshotManager  # noqa: E402

cfg = Config.from_params({
    "objective": "binary", "tree_learner": "data", "num_leaves": "8",
    "min_data_in_leaf": "5", "min_sum_hessian_in_leaf": "1",
    "hist_dtype": "float64", "metric": "", "is_save_binary_file": "false"})

with trace_collectives() as events:
    from lightgbm_tpu.io.dataset import load_dataset
    from lightgbm_tpu.models.gbdt import create_boosting
    from lightgbm_tpu.objectives import create_objective

    ds = load_dataset(data, cfg, rank=rank, num_shards=nproc)
    obj = create_objective(cfg)
    obj.init(ds.metadata, ds.num_data)
    booster = create_boosting(cfg, ds, obj)
    # the early-stop agreement hook, wired exactly as cli.init_train
    # wires it for num_machines > 1 — collectives it fires dispatch
    # DYNAMICALLY (the static model can't bind them; the parent test
    # accepts them via the registered stop_sync hook)
    booster.stop_sync = vote_any
    # resume agreement over an empty snapshot dir: every rank gathers
    # its (empty) valid-iteration window and agrees on a fresh start
    snaps = SnapshotManager(snap_dir, period=1, resume="auto",
                            rank=rank, num_machines=nproc)
    assert snaps.maybe_resume(booster) == 0
    for _ in range(3):
        booster.train_one_iter(None, None, False)
    # one preemption sync, as cli.train runs per segment
    assert snaps.sync_flag(False) is False

doc = [dict(name=e.name, shape=list(e.shape), dtype=e.dtype,
            callsite=e.callsite) for e in events]
with open(trace_out, "w") as f:
    json.dump(doc, f, indent=1)
print("worker %d traced %d collective(s)" % (rank, len(doc)))
