"""Iteration-batched training (config.iter_batch): K boosting rounds
scanned into one device dispatch must be BIT-PARITY with the
per-iteration oracle (iter_batch=1).

The scan wrapper (models/gbdt.py _batch_iters) iterates the very same
fused step closure the K=1 path jits, and the segment scheduler
(_plan_segment) ends segments at every host-observable boundary
(metric lines, early stopping, re-bagging epochs, re-sort cadence,
checkpoints), so the model TEXT — not just the structure — must be
byte-identical for any K, including an odd K that does not divide the
round count.  K values cover {2, 8, odd non-divisor 3}; the axes cover
{binary, regression, multiclass, lambdarank} x {plain, bagged with a
re-bag boundary INSIDE the requested segment} x DART x
tree_learner=data, plus checkpoint/resume mid-segment and early
stopping at the same iteration.
"""

import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import Config
from lightgbm_tpu.models.gbdt import create_boosting
from lightgbm_tpu.objectives import create_objective


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _data_for(objective, n, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 6).astype(np.float32)
    signal = x[:, 0] + 0.5 * x[:, 1] * x[:, 2] + 0.3 * rng.randn(n)
    if objective == "binary":
        return x, (signal > 0).astype(np.float32), None
    if objective == "regression":
        return x, signal.astype(np.float32), None
    if objective == "multiclass":
        edges = np.quantile(signal, [1 / 3, 2 / 3])
        return x, np.digitize(signal, edges).astype(np.float32), None
    assert objective == "lambdarank"
    y = np.clip(np.round(signal + 1.5), 0, 4).astype(np.float32)
    return x, y, np.full(n // 16, 16, dtype=np.int32)


def _params_for(objective):
    p = {"objective": objective, "num_leaves": 7, "max_bin": 63,
         "min_data_in_leaf": 20, "learning_rate": 0.1, "metric": ""}
    if objective == "multiclass":
        p.update(num_class=3, metric="multi_logloss")
    return p


def _model_text(params, x, y, group=None, rounds=10):
    ds = lgb.Dataset(x, label=y, group=group)
    b = lgb.train(params, ds, num_boost_round=rounds, verbose_eval=False)
    return b.model_to_string()


# ---------------------------------------------------------------------------
# the parity matrix: objectives x K, plain and bagged
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("objective",
                         ["binary", "regression", "multiclass",
                          "lambdarank"])
def test_batched_matches_oracle(objective):
    """Model text byte-identity for K in {2, 8, odd non-divisor 3}
    against the K=1 oracle, 10 rounds (so K=8 leaves a short final
    segment and K=3 never tiles the count)."""
    n = 1600
    x, y, group = _data_for(objective, n, seed=11)
    base = _params_for(objective)
    oracle = _model_text({**base, "iter_batch": "1"}, x, y, group)
    for k in ("2", "8", "3"):
        got = _model_text({**base, "iter_batch": k}, x, y, group)
        assert got == oracle, "iter_batch=%s diverged (%s)" % (
            k, objective)


@pytest.mark.parametrize("objective", ["binary", "multiclass"])
def test_batched_bagged_rebag_inside_segment(objective):
    """bagging_freq=3 with iter_batch=8: every requested segment
    straddles a re-bagging boundary, so the scheduler must cut segments
    at the epoch edge — models stay byte-identical and mask draws stay
    on the sequential mt19937 stream."""
    n = 1600
    x, y, group = _data_for(objective, n, seed=5)
    base = {**_params_for(objective), "bagging_fraction": 0.5,
            "bagging_freq": 3}
    oracle = _model_text({**base, "iter_batch": "1"}, x, y, group,
                         rounds=9)
    for k in ("8", "2"):
        got = _model_text({**base, "iter_batch": k}, x, y, group,
                          rounds=9)
        assert got == oracle, "bagged iter_batch=%s diverged" % k


def test_batched_dart_matches_oracle():
    """DART banked path: drop lotteries, 1/(1+k) shrinkages and
    normalization factors precompute host-side and feed the scan as
    stacked inputs; the f64 drop-factor replay must see the identical
    per-iteration history."""
    x, y, _ = _data_for("binary", 1600, seed=3)
    base = {**_params_for("binary"), "boosting_type": "dart"}
    oracle = _model_text({**base, "iter_batch": "1"}, x, y, rounds=10)
    for k in ("8", "3"):
        got = _model_text({**base, "iter_batch": k}, x, y, rounds=10)
        assert got == oracle, "dart iter_batch=%s diverged" % k


def test_batched_dart_bagged_matches_oracle():
    x, y, _ = _data_for("binary", 1600, seed=4)
    base = {**_params_for("binary"), "boosting_type": "dart",
            "bagging_fraction": 0.5, "bagging_freq": 2}
    oracle = _model_text({**base, "iter_batch": "1"}, x, y, rounds=8)
    got = _model_text({**base, "iter_batch": "8"}, x, y, rounds=8)
    assert got == oracle


@pytest.mark.parametrize("objective", ["binary", "lambdarank"])
def test_batched_data_parallel_matches_oracle(objective):
    """tree_learner=data (single host, 8 virtual devices): the scan
    wraps the body INSIDE shard_map, so per-step psums stay put and
    the replicated [K, F] feature-mask specs cover the stacked xs.
    lambdarank rides its query-granular shard layout through the same
    wrapper (layout state is segment-constant, closed over)."""
    x, y, group = _data_for(objective, 2048, seed=7)
    base = {**_params_for(objective), "tree_learner": "data"}
    oracle = _model_text({**base, "iter_batch": "1"}, x, y, group,
                         rounds=6)
    got = _model_text({**base, "iter_batch": "4"}, x, y, group,
                      rounds=6)
    assert got == oracle


def test_batched_ordered_reorder_scan_matches_oracle():
    """hist_reorder_every=1 makes EVERY iteration a re-sort, so the
    segment scans the REORDER body (bins/bag/gstate/row order ride the
    carry); cadence > 1 segments between re-sorts.  Pallas interpret
    mode exercises the real ordered-partition kernel path on CPU."""
    x, y, _ = _data_for("binary", 8192, seed=8)
    for every in ("1", "3"):
        base = {**_params_for("binary"), "hist_impl": "pallas",
                "hist_ordered": "auto", "hist_reorder_every": every}
        oracle = _model_text({**base, "iter_batch": "1"}, x, y, rounds=6)
        got = _model_text({**base, "iter_batch": "4"}, x, y, rounds=6)
        assert got == oracle, "reorder_every=%s diverged" % every


# ---------------------------------------------------------------------------
# boundaries: early stopping, metrics, checkpoints
# ---------------------------------------------------------------------------

def test_early_stopping_same_iteration():
    """Early stopping checks run every iteration in the reference, so
    an early-stop config forces K=1 segments — the stopped iteration
    and the saved model must match the oracle exactly."""
    x, y, _ = _data_for("binary", 1200, seed=2)
    xv, yv, _ = _data_for("binary", 400, seed=12)
    out = {}
    for k in ("1", "8"):
        params = {**_params_for("binary"), "metric": "binary_logloss",
                  "iter_batch": k}
        ds = lgb.Dataset(x, label=y)
        dv = lgb.Dataset(xv, label=yv, reference=ds)
        b = lgb.train(params, ds, num_boost_round=40, valid_sets=[dv],
                      early_stopping_rounds=3, verbose_eval=False)
        out[k] = (b.current_iteration, b.model_to_string())
    assert out["1"] == out["8"]


def test_metric_lines_unchanged(capsys):
    """metric_freq=2 with iter_batch=8: segments end at every metric
    boundary, so the logged metric lines (iteration numbers AND values)
    are identical to the oracle's."""
    x, y, _ = _data_for("binary", 1200, seed=6)
    xv, yv, _ = _data_for("binary", 400, seed=16)
    lines = {}
    for k in ("1", "8"):
        params = {**_params_for("binary"), "metric": "binary_logloss",
                  "metric_freq": 2, "iter_batch": k}
        ds = lgb.Dataset(x, label=y)
        dv = lgb.Dataset(xv, label=yv, reference=ds)
        capsys.readouterr()
        lgb.train(params, ds, num_boost_round=8, valid_sets=[dv],
                  verbose_eval=2)
        lines[k] = [ln for ln in capsys.readouterr().out.splitlines()
                    if "Iteration:" in ln]
    assert lines["1"] == lines["8"] and lines["1"]


def test_checkpoint_resume_mid_segment():
    """A checkpoint taken off the K grid (after 3 iters, iter_batch=8)
    resumes bit-for-bit: segment planning restarts from the restored
    absolute iteration, so the remaining segments retile without
    drifting any draw or boundary."""
    import tempfile

    x, y, _ = _data_for("binary", 1200, seed=9)
    params = {"objective": "binary", "num_leaves": 7, "max_bin": 63,
              "min_data_in_leaf": 20, "metric": "",
              "bagging_fraction": 0.5, "bagging_freq": 2,
              "iter_batch": "8", "num_iterations": 8}
    ds = lgb.Dataset(x, label=y, params=params)

    def fresh(ib):
        cfg = Config.from_params({**{k: str(v) for k, v in
                                     params.items()}, "iter_batch": ib})
        inner = ds.inner
        obj = create_objective(cfg)
        obj.init(inner.metadata, inner.num_data)
        return create_boosting(cfg, inner, obj)

    ck = os.path.join(tempfile.mkdtemp(), "ibck.npz")
    a = fresh("8")
    done = 0
    while done < 3:
        _, k = a.train_segment(3 - done, is_eval=False)
        done += k
    a.save_checkpoint(ck)
    while done < 8:
        _, k = a.train_segment(8 - done, is_eval=False)
        done += k

    b = fresh("8")
    b.load_checkpoint(ck)
    done = b.iter
    while done < 8:
        _, k = b.train_segment(8 - done, is_eval=False)
        done += k

    # and the K=1 oracle end-to-end
    c = fresh("1")
    for _ in range(8):
        c.train_one_iter(None, None, False)

    ma, mb, mc = a.models, b.models, c.models
    assert len(ma) == len(mb) == len(mc) == 8
    for t1, t2, t3 in zip(ma, mb, mc):
        assert t1.to_string() == t2.to_string() == t3.to_string()


# ---------------------------------------------------------------------------
# segment scheduling (host logic, no training dispatch needed)
# ---------------------------------------------------------------------------

def _booster(extra=None, n=400, objective="binary"):
    x, y, group = _data_for(objective, n, seed=1)
    params = {**_params_for(objective), "min_data_in_leaf": 5,
              **(extra or {})}
    ds = lgb.Dataset(x, label=y, group=group,
                     params={k: str(v) for k, v in params.items()})
    cfg = Config.from_params({k: str(v) for k, v in params.items()})
    obj = create_objective(cfg)
    obj.init(ds.inner.metadata, ds.inner.num_data)
    return create_boosting(cfg, ds.inner, obj)

def test_plan_caps_at_rebag_boundary():
    g = _booster({"iter_batch": "8", "bagging_fraction": 0.5,
                  "bagging_freq": 3})
    assert g._plan_segment(100, is_eval=False) == 3
    g.iter = 2          # next re-bag at 3: one iteration left in epoch
    assert g._plan_segment(100, is_eval=False) == 1
    g.iter = 3          # ON the boundary: a full epoch fits
    assert g._plan_segment(100, is_eval=False) == 3


def test_plan_caps_at_metric_boundary_and_early_stop():
    g = _booster({"iter_batch": "8", "metric": "binary_logloss",
                  "metric_freq": 5})
    # no valid sets and no training metrics attached -> metrics inactive
    assert g._plan_segment(100, is_eval=True) == 8
    from lightgbm_tpu.metrics import create_metrics
    m = create_metrics(g.config)[0]
    m.init("training", g.train_data.metadata, g.train_data.num_data)
    g.training_metrics = [m]
    assert g._plan_segment(100, is_eval=True) == 5
    assert g._plan_segment(100, is_eval=False) == 8
    g.early_stopping_round = 2
    assert g._plan_segment(100, is_eval=True) == 1


def test_plan_remaining_and_disable():
    g = _booster({"iter_batch": "8"})
    assert g._plan_segment(3, is_eval=False) == 3
    assert g._plan_segment(100, is_eval=False) == 8
    g2 = _booster({"iter_batch": "1"})
    assert g2._plan_segment(100, is_eval=False) == 1


def test_auto_k_divides_metric_freq():
    g = _booster({"iter_batch": "auto", "metric": "binary_logloss",
                  "metric_freq": 6})
    # this suite runs on the CPU backend, where auto resolves to the
    # per-iteration oracle (local dispatch is cheap; the K-scan exists
    # to kill remote-attached dispatch round-trips)
    assert g._auto_iter_batch() == 1
    # the accelerator policy: default 8, shrunk to the largest divisor
    # of metric_freq once metric output is live
    assert g._auto_iter_batch_accel() == 8     # metrics not attached yet
    from lightgbm_tpu.metrics import create_metrics
    m = create_metrics(g.config)[0]
    m.init("training", g.train_data.metadata, g.train_data.num_data)
    g.training_metrics = [m]
    assert g._auto_iter_batch_accel() == 6     # largest divisor of 6 <= 8
    g.config.metric_freq = 10
    assert g._auto_iter_batch_accel() == 5
    g.config.metric_freq = 1
    assert g._auto_iter_batch_accel() == 1


def test_iter_batch_config_validation():
    from lightgbm_tpu.utils.log import LightGBMError

    with pytest.raises(LightGBMError):
        Config.from_params({"iter_batch": "0"})
    with pytest.raises(LightGBMError):
        Config.from_params({"iter_batch": "bogus"})
    assert Config.from_params({"iter_batch": "4"}).iter_batch == "4"
    assert Config.from_params({}).iter_batch == "auto"


# ---------------------------------------------------------------------------
# real 2-process multi-host run
# ---------------------------------------------------------------------------

def test_multihost_batched_two_process(tmp_path):
    """2 jax processes x 4 virtual CPU devices run tree_learner=data
    through the MULTI-HOST fused sharded step with iter_batch=4 and
    iter_batch=1; ranks must agree and K=4 must reproduce the K=1
    model bytes."""
    import socket as socketlib
    import subprocess
    import sys

    rng = np.random.RandomState(0)
    n, ncol = 800, 5
    x = rng.randn(n, ncol)
    y = (x[:, 0] + 0.5 * x[:, 1] > 0).astype(int)
    data = tmp_path / "train.tsv"
    data.write_text("\n".join(
        "\t".join([str(y[i])] + ["%f" % v for v in x[i]])
        for i in range(n)) + "\n")

    s = socketlib.socket()
    s.bind(("localhost", 0))
    port = str(s.getsockname()[1])
    s.close()

    outs = [str(tmp_path / ("model_%d" % r)) for r in range(2)]
    worker = os.path.join(os.path.dirname(__file__),
                          "mh_iterbatch_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [subprocess.Popen(
        [sys.executable, worker, str(r), "2", port, str(data), outs[r]],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
        for r in range(2)]
    logs = [p.communicate(timeout=600)[0].decode() for p in procs]
    for r, p in enumerate(procs):
        assert p.returncode == 0, "worker %d failed:\n%s" % (r, logs[r])

    k1_0 = open(outs[0] + "_k1.txt").read()
    k4_0 = open(outs[0] + "_k4.txt").read()
    assert k1_0 == open(outs[1] + "_k1.txt").read(), \
        "ranks diverged (K=1)"
    assert k4_0 == open(outs[1] + "_k4.txt").read(), \
        "ranks diverged (K=4)"
    assert k4_0 == k1_0, "iter_batch=4 diverged from the K=1 oracle"
    assert "batched_segments=1" in logs[0]
