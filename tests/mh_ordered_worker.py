"""Worker for the multi-host ORDERED-partition fused test
(test_parallel.py::test_multihost_ordered_fused_matches_unordered).

Usage: python mh_ordered_worker.py <rank> <nproc> <port> <data> <out>
                                   <hist_ordered>

Each worker owns 4 virtual CPU devices, joins jax.distributed, loads its
lottery row shard, and trains tree_learner=data through the MULTI-HOST
fused shard_map step with the Pallas (interpret-mode) histogram kernel —
hist_ordered=auto exercises the round-5 mh reorder path: global-position
row order, shard-local re-sorts, permuted global bag masks and gradient
state.  Bagging + feature_fraction compose on top.
"""

import os
import sys

rank, nproc, port, data, out, ordered = (
    int(sys.argv[1]), int(sys.argv[2]), sys.argv[3], sys.argv[4],
    sys.argv[5], sys.argv[6])
# switch=1: leave the fused path mid-training via custom gradients
# (the ADVICE r5 bins_dev regression — see below) instead of the
# checkpoint/resume leg
switch = int(sys.argv[7]) if len(sys.argv) > 7 else 0
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=4")
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    # cross-process collectives on the CPU backend need the gloo
    # implementation (without it the compiler rejects multiprocess
    # computations outright on CPU-only boxes)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
except Exception:
    pass
jax.distributed.initialize(coordinator_address="localhost:" + port,
                           num_processes=nproc, process_id=rank)

from lightgbm_tpu.config import Config  # noqa: E402
from lightgbm_tpu.io.dataset import load_dataset  # noqa: E402
from lightgbm_tpu.models.gbdt import create_boosting  # noqa: E402
from lightgbm_tpu.objectives import create_objective  # noqa: E402

cfg = Config.from_params({
    "objective": "binary", "tree_learner": "data", "num_leaves": "15",
    "min_data_in_leaf": "20", "hist_impl": "pallas",
    "hist_dtype": "float32", "hist_ordered": ordered,
    "hist_reorder_every": "2", "bagging_fraction": "0.8",
    "bagging_freq": "3", "feature_fraction": "0.8", "metric": "",
    "is_save_binary_file": "false"})
ds = load_dataset(data, cfg, rank=rank, num_shards=nproc)
obj = create_objective(cfg)
obj.init(ds.metadata, ds.num_data)
booster = create_boosting(cfg, ds, obj)
if switch:
    # custom gradients for the mid-training fused-path exit below: a
    # pure function of the GLOBAL row id, so every cluster mode feeds
    # identical values (computed up front, before training starts)
    import numpy as np
    grad_sw = np.sin(0.37 * ds.local_rows).astype(np.float32)
    hess_sw = (0.6 + 0.4 * np.cos(0.11 * ds.local_rows)).astype(np.float32)
assert booster._mh_fused and booster._can_fuse(), "must take mh fused path"
if ordered != "off":
    assert booster.hist_ranged, "ordered mode must be active"
for _ in range(3):
    booster.train_one_iter(None, None, False)
if ordered != "off":
    assert booster._row_order is not None, "mh re-sort must have run"

if switch:
    # regression (ADVICE r5 medium, gbdt._restore_row_order): leaving
    # the multi-host fused path via CUSTOM gradients while an ordered-
    # partition row order is active must rebuild the global bins_dev
    # from FILE order — before the fix the general path kept growing
    # later trees on leaf-permuted bins against file-order gradients,
    # silently corrupting every subsequent tree.
    booster.train_one_iter(grad_sw, hess_sw, False)
    assert not booster._mh_fused, "custom grads must exit the fused path"
    for _ in range(2):
        booster.train_one_iter(None, None, False)
else:
    # exact-state checkpoint/resume under the multi-host fused path:
    # each rank snapshots ITS file-order block + its slice of the
    # global row order; a fresh booster restored from it must continue
    # bit-for-bit
    ckpt = out + ".rank%d.ckpt" % rank
    booster.save_checkpoint(ckpt)
    resumed = create_boosting(cfg, ds, obj)
    resumed.load_checkpoint(ckpt)
    for b in (booster, resumed):
        for _ in range(3):
            b.train_one_iter(None, None, False)
    ma = "".join(t.to_string() for t in booster.models)
    mb = "".join(t.to_string() for t in resumed.models)
    assert ma == mb, "mh checkpoint resume diverged from uninterrupted run"

booster.save_model_to_file(-1, True, out)
print("worker %d done (%s): %d trees" % (rank, ordered,
                                         len(booster.models)))
