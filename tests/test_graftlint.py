"""graftlint + typegate unit tests, and the repo-is-clean gates.

The rule tests lint small in-memory modules through lint_source() at a
chosen package-relative path, so each rule's trigger and non-trigger
are pinned independently of the real tree.  The final tests run the
full linter over the installed package — the same check scripts/lint.sh
gates on — so a hot-path invariant regression fails the suite even if
nobody runs the lint script.
"""

import textwrap

from lightgbm_tpu.analysis.graftlint import lint_source, run_graftlint
from lightgbm_tpu.analysis.typegate import check_source, run_typegate


def lint(src, relpath="ops/some_kernel.py"):
    return lint_source(textwrap.dedent(src), relpath)


def rules_of(findings):
    return [f.rule for f in findings]


# ---------------------------------------------------------------------------
# GL001 host-sync-in-traced-fn
# ---------------------------------------------------------------------------

def test_item_in_jitted_function_flagged():
    out = lint("""
        import jax

        @jax.jit
        def f(x):
            return x.sum().item()
    """)
    assert "GL001" in rules_of(out)


def test_item_outside_trace_not_flagged():
    out = lint("""
        def f(x):
            return x.sum().item()
    """)
    assert "GL001" not in rules_of(out)


def test_np_asarray_in_jitted_function_flagged():
    out = lint("""
        import jax
        import numpy as np

        @jax.jit
        def f(x):
            return np.asarray(x) + 1
    """)
    assert "GL001" in rules_of(out)


def test_float_on_traced_value_flagged_but_shape_ok():
    out = lint("""
        import jax

        @jax.jit
        def f(x):
            n = float(x.shape[0])   # static: fine
            return x * n

        @jax.jit
        def g(x):
            return float(x)         # concretizes a tracer
    """)
    assert rules_of(out).count("GL001") == 1


def test_static_argname_params_not_tainted():
    out = lint("""
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("k",))
        def f(x, *, k):
            return x * int(k)
    """)
    assert "GL001" not in rules_of(out)


def test_fused_step_maker_closure_is_traced():
    # the gbdt pattern: jax.jit(_maker(...)) traces the returned closure
    out = lint("""
        import jax

        def _step_body(lr):
            def step(scores):
                return float(scores) * lr
            return step

        def make(lr):
            return jax.jit(_step_body(lr), donate_argnums=(0,))
    """)
    assert "GL001" in rules_of(out)


def test_lax_scan_body_is_traced():
    # scan bodies are traced; their host syncs classify as GL012 (the
    # scan-carry sharpening of GL001) since the iteration-batched
    # training loop landed
    out = lint("""
        import jax

        def outer(xs):
            def body(c, x):
                return c, x.item()
            return jax.lax.scan(body, 0, xs)
    """)
    assert "GL012" in rules_of(out)


# ---------------------------------------------------------------------------
# GL002 jax-import-in-jax-free-module
# ---------------------------------------------------------------------------

def test_module_level_jax_import_in_jax_free_module():
    out = lint("import jax\n", relpath="predict_fast.py")
    assert "GL002" in rules_of(out)


def test_function_local_jax_import_is_fine():
    out = lint("""
        def f():
            import jax
            return jax
    """, relpath="predict_fast.py")
    assert "GL002" not in rules_of(out)


def test_jax_free_module_importing_jaxful_module_flagged():
    out = lint("from .models.gbdt import GBDT\n", relpath="predict_fast.py")
    assert "GL002" in rules_of(out)


def test_jax_free_module_importing_jax_free_module_ok():
    out = lint("from .models.tree import Tree\n", relpath="predict_fast.py")
    assert "GL002" not in rules_of(out)


def test_non_contract_module_may_import_jax():
    out = lint("import jax\n", relpath="objectives.py")
    assert "GL002" not in rules_of(out)


def test_conditionally_guarded_module_level_jax_import_flagged():
    # an `if`/`try` guard still executes at import time — only
    # TYPE_CHECKING blocks are exempt (they never run)
    out = lint("""
        import os
        if os.environ.get("X"):
            import jax
    """, relpath="predict_fast.py")
    assert "GL002" in rules_of(out)
    out = lint("""
        try:
            import jax
        except ImportError:
            jax = None
    """, relpath="predict_fast.py")
    assert "GL002" in rules_of(out)
    out = lint("""
        from typing import TYPE_CHECKING
        if TYPE_CHECKING:
            import jax
    """, relpath="predict_fast.py")
    assert "GL002" not in rules_of(out)


def test_absolute_form_package_import_resolved():
    # `from lightgbm_tpu.models.gbdt import ...` must flag exactly like
    # the relative form
    out = lint("from lightgbm_tpu.models.gbdt import GBDT\n",
               relpath="predict_fast.py")
    assert "GL002" in rules_of(out)
    out = lint("import lightgbm_tpu.models.gbdt\n",
               relpath="predict_fast.py")
    assert "GL002" in rules_of(out)
    out = lint("from lightgbm_tpu.models.tree import Tree\n",
               relpath="predict_fast.py")
    assert "GL002" not in rules_of(out)


# ---------------------------------------------------------------------------
# GL003 float64-in-device-code
# ---------------------------------------------------------------------------

def test_float64_in_jit_flagged_host_ok():
    out = lint("""
        import jax
        import jax.numpy as jnp
        import numpy as np

        @jax.jit
        def f(x):
            return x.astype(jnp.float64)

        def host(x):
            return np.asarray(x, dtype=np.float64)
    """)
    assert rules_of(out).count("GL003") == 1


def test_dtype_string_float64_in_jit_flagged():
    out = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return jnp.zeros(3, dtype="float64") + x
    """)
    assert "GL003" in rules_of(out)


# ---------------------------------------------------------------------------
# GL004 jit-missing-static
# ---------------------------------------------------------------------------

def test_kwonly_param_without_static_flagged():
    out = lint("""
        import jax

        @jax.jit
        def f(x, *, max_bin: int = 255):
            return x + max_bin
    """)
    assert "GL004" in rules_of(out)


def test_kwonly_param_with_static_ok():
    out = lint("""
        import functools
        import jax

        @functools.partial(jax.jit, static_argnames=("max_bin",))
        def f(x, *, max_bin: int = 255):
            return x + max_bin
    """)
    assert "GL004" not in rules_of(out)


def test_static_argnums_resolved_positionally():
    out = lint("""
        import jax

        def f(x, n_pad: int):
            return x[:n_pad]

        g = jax.jit(f, static_argnums=1)
    """)
    assert "GL004" not in rules_of(out)


# ---------------------------------------------------------------------------
# GL005 wallclock-or-rng-in-parity-path
# ---------------------------------------------------------------------------

def test_time_in_parity_module_flagged():
    out = lint("""
        import time

        def f():
            return time.time()
    """, relpath="ops/grow.py")
    assert "GL005" in rules_of(out)


def test_np_random_in_parity_module_flagged():
    out = lint("""
        import numpy as np

        def f(n):
            return np.random.rand(n)
    """, relpath="io/binning.py")
    assert "GL005" in rules_of(out)


def test_time_outside_parity_modules_ok():
    out = lint("import time\nT0 = time.monotonic()\n",
               relpath="serving/forest.py")
    assert "GL005" not in rules_of(out)


# ---------------------------------------------------------------------------
# GL006 unlocked-serving-mutation
# ---------------------------------------------------------------------------

_SERVING_SRC = """
    import threading

    class S:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def locked_inc(self):
            with self._lock:
                self.n += 1

        def unlocked_inc(self):
            self.n += 1
"""


def test_unlocked_store_in_serving_flagged_locked_ok():
    out = lint(_SERVING_SRC, relpath="serving/server.py")
    assert rules_of(out).count("GL006") == 1


def test_same_code_outside_serving_not_flagged():
    out = lint(_SERVING_SRC, relpath="models/gbdt.py")
    assert "GL006" not in rules_of(out)


def test_subscript_mutation_of_shared_state_flagged():
    # `self.requests[k] = ...` mutates shared state exactly like a
    # plain store — the Metrics counter shape the rule exists to audit
    out = lint("""
        import threading

        class M:
            def __init__(self):
                self._lock = threading.Lock()
                self.requests = {}
                self.counts = [0, 0]

            def unlocked(self, k):
                self.requests[k] = self.requests.get(k, 0) + 1
                self.counts[0] += 1

            def locked(self, k):
                with self._lock:
                    self.requests[k] = self.requests.get(k, 0) + 1
    """, relpath="serving/server.py")
    assert rules_of(out).count("GL006") == 2


# ---------------------------------------------------------------------------
# GL007 global-jax-config-mutation
# ---------------------------------------------------------------------------

def test_x64_toggle_outside_entry_points_flagged():
    src = """
        import jax

        def f():
            jax.config.update("jax_enable_x64", True)
    """
    assert "GL007" in rules_of(lint(src, relpath="ops/predict.py"))
    assert "GL007" not in rules_of(lint(src, relpath="cli.py"))


def test_cache_dir_config_not_flagged():
    out = lint("""
        import jax

        def f(d):
            jax.config.update("jax_compilation_cache_dir", d)
    """, relpath="utils/compile_cache.py")
    assert "GL007" not in rules_of(out)


# ---------------------------------------------------------------------------
# GL008 stdout-bypasses-logger
# ---------------------------------------------------------------------------

def test_print_in_library_flagged():
    out = lint("def f():\n    print('hi')\n", relpath="models/gbdt.py")
    assert "GL008" in rules_of(out)


def test_logger_home_exempt():
    out = lint("import sys\n\n\ndef w(m):\n    sys.stdout.write(m)\n",
               relpath="utils/log.py")
    assert "GL008" not in rules_of(out)


# ---------------------------------------------------------------------------
# suppressions: GL009 / GL010 and the happy path
# ---------------------------------------------------------------------------

def test_justified_suppression_silences_finding():
    out = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            # graftlint: disable=GL003 -- f64 is this kernel's contract
            # with the host accumulator (x64-only predict path)
            return x.astype(jnp.float64)
    """)
    assert rules_of(out) == []


def test_suppression_without_justification_is_gl009():
    out = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            return x.astype(jnp.float64)  # graftlint: disable=GL003
    """)
    rules = rules_of(out)
    assert "GL009" in rules       # bare suppression
    assert "GL003" not in rules   # ... but it still suppresses


def test_stale_suppression_is_gl010():
    out = lint("""
        def f(x):
            # graftlint: disable=GL003 -- nothing here actually needs it
            return x + 1
    """)
    assert rules_of(out) == ["GL010"]


def test_multi_rule_suppression_reports_stale_half():
    # disable=GL003,GL006 where only GL003 fires: the GL006 half is
    # stale and must be reported (per-rule staleness, not per-comment)
    out = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def f(x):
            # graftlint: disable=GL003,GL006 -- f64 contract holds here
            return x.astype(jnp.float64)
    """)
    rules = rules_of(out)
    assert "GL003" not in rules      # suppressed half works
    assert rules.count("GL010") == 1  # stale GL006 half reported


def test_unknown_rule_in_suppression_is_gl009():
    out = lint("""
        def f(x):
            return x + 1  # graftlint: disable=GL999 -- no such rule here
    """)
    assert "GL009" in rules_of(out)


def test_suppression_inside_docstring_ignored():
    out = lint('''
        def f(x):
            """Example: # graftlint: disable=GL003 -- doc text only."""
            return x + 1
    ''')
    assert rules_of(out) == []


# ---------------------------------------------------------------------------
# typegate
# ---------------------------------------------------------------------------

def test_typegate_flags_missing_annotations():
    out = check_source(textwrap.dedent("""
        def f(a, b: int):
            return a + b
    """))
    msgs = [f.message for f in out]
    assert any("unannotated parameter" in m and "a" in m for m in msgs)
    assert any("missing return annotation" in m for m in msgs)


def test_typegate_accepts_annotated_and_init():
    out = check_source(textwrap.dedent("""
        class C:
            def __init__(self, x: int):
                self.x = x

            def get(self) -> int:
                return self.x
    """))
    assert out == []


def test_typegate_zero_param_init_needs_return_annotation():
    # mypy only infers -> None for __init__ when at least one param is
    # annotated; a bare `def __init__(self):` is untyped under strict
    out = check_source(textwrap.dedent("""
        class C:
            def __init__(self):
                self.x = 1
    """))
    assert any("missing return annotation" in f.message for f in out)
    out = check_source(textwrap.dedent("""
        class C:
            def __init__(self) -> None:
                self.x = 1
    """))
    assert out == []


# ---------------------------------------------------------------------------
# GL011 static-bag-shape
# ---------------------------------------------------------------------------

def test_gl011_nonstatic_bag_size_jit_param_flagged():
    out = lint("""
        import jax

        @jax.jit
        def step(scores, bag_rows):
            return scores[:bag_rows]
    """)
    assert "GL011" in rules_of(out)


def test_gl011_static_bag_size_jit_param_clean():
    out = lint("""
        import functools

        import jax

        @functools.partial(jax.jit, static_argnames=("bag_rows",))
        def step(scores, bag_rows):
            return scores[:bag_rows]
    """)
    assert "GL011" not in rules_of(out)


def test_gl011_bag_mask_param_not_a_bag_size():
    """Masks are genuine traced row data — only COUNT/SIZE names are
    static shapes."""
    out = lint("""
        import jax

        @jax.jit
        def step(scores, bag_mask):
            return scores * bag_mask
    """)
    assert "GL011" not in rules_of(out)


def test_gl011_int_on_traced_bag_count_flagged_over_gl001():
    out = lint("""
        import jax
        import jax.numpy as jnp

        @jax.jit
        def step(bag_cnt, scores):
            return scores[:int(bag_cnt)]
    """)
    rules = rules_of(out)
    assert "GL011" in rules
    assert "GL001" not in rules     # the specific rule wins


def test_gl011_item_on_bag_window_attr_flagged():
    out = lint("""
        import jax

        @jax.jit
        def step(state):
            w = state.bag_window.item()
            return w
    """)
    assert "GL011" in rules_of(out)


def test_gl011_int_on_plain_traced_value_still_gl001():
    out = lint("""
        import jax

        @jax.jit
        def step(x):
            return int(x)
    """)
    rules = rules_of(out)
    assert "GL001" in rules and "GL011" not in rules


def test_gl011_suppressible_with_justification():
    out = lint("""
        import jax

        @jax.jit
        def step(scores, bag_rows):  # graftlint: disable=GL011 -- \
bench-only probe; retrace per epoch is the point being measured
            return scores[:bag_rows]
    """)
    assert "GL011" not in rules_of(out)


# ---------------------------------------------------------------------------
# GL012 host-sync-in-scan-carry
# ---------------------------------------------------------------------------

def test_gl012_item_on_scan_carry_flagged():
    out = lint("""
        import jax

        def batched(scores, xs):
            def body(carry, fmask):
                carry = carry + fmask.sum()
                _ = carry.item()
                return carry, fmask
            return jax.lax.scan(body, scores, xs)
    """)
    rules = rules_of(out)
    assert "GL012" in rules and "GL001" not in rules


def test_gl012_int_on_per_iteration_value_flagged():
    out = lint("""
        import jax

        def batched(scores, xs):
            def body(carry, x):
                return carry, int(x)
            return jax.lax.scan(body, scores, xs)
    """)
    assert "GL012" in rules_of(out)


def test_gl012_device_get_in_scan_body_flagged():
    out = lint("""
        import jax

        def batched(scores, xs):
            def body(carry, x):
                return carry, jax.device_get(x)
            return jax.lax.scan(body, scores, xs)
    """)
    assert "GL012" in rules_of(out)


def test_gl012_nested_helper_inside_scan_body_flagged():
    out = lint("""
        import jax
        import numpy as np

        def batched(scores, xs):
            def body(carry, x):
                def inner(v):
                    return np.asarray(v)
                return carry, inner(x)
            return jax.lax.scan(body, scores, xs)
    """)
    assert "GL012" in rules_of(out)


def test_gl012_clean_scan_body_not_flagged():
    out = lint("""
        import jax
        import jax.numpy as jnp

        def batched(scores, xs):
            def body(carry, fmask):
                return carry + jnp.sum(fmask), fmask
            return jax.lax.scan(body, scores, xs)
    """)
    assert "GL012" not in rules_of(out)


def test_gl012_same_named_def_outside_scan_scope_stays_gl001():
    # two inner defs named `body` (the codebase's own inner-fn naming
    # convention): only the one lexically visible to the lax.scan call
    # is a scan body — the jitted sibling's sync stays GL001
    out = lint("""
        import jax

        def batched(scores, xs):
            def body(carry, x):
                return carry + x, x
            return jax.lax.scan(body, scores, xs)

        def other(scores):
            def body(x):
                return x.sum().item()
            return jax.jit(body)(scores)
    """)
    rules = rules_of(out)
    assert "GL001" in rules and "GL012" not in rules


def test_gl012_sync_outside_scan_stays_gl001():
    out = lint("""
        import jax

        @jax.jit
        def step(scores):
            return scores.sum().item()
    """)
    rules = rules_of(out)
    assert "GL001" in rules and "GL012" not in rules


def test_gl012_bag_count_inside_scan_still_gl011():
    out = lint("""
        import jax

        def batched(scores, xs):
            def body(carry, x):
                bag_rows = carry.sum()
                return carry, int(bag_rows)
            return jax.lax.scan(body, scores, xs)
    """)
    rules = rules_of(out)
    assert "GL011" in rules and "GL012" not in rules


def test_gl012_suppressible_with_justification():
    out = lint("""
        import jax

        def batched(scores, xs):
            def body(carry, x):
                # graftlint: disable=GL012 -- debug probe kept behind an
                # env flag; never runs in the batched training loop
                return carry, x.item()
            return jax.lax.scan(body, scores, xs)
    """)
    assert "GL012" not in rules_of(out)


# ---------------------------------------------------------------------------
# the gates scripts/lint.sh relies on: the repo itself is clean
# ---------------------------------------------------------------------------

def test_repo_is_graftlint_clean():
    findings = run_graftlint()
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


def test_repo_passes_typegate():
    findings = run_typegate()
    assert findings == [], "\n" + "\n".join(f.render() for f in findings)


# ---------------------------------------------------------------------------
# GL002 discovery: the jax-free set comes from __jax_free__ markers
# ---------------------------------------------------------------------------

def test_marker_declares_module_jax_free():
    out = lint("""
        __jax_free__ = True
        import jax
    """, relpath="some/new_module.py")
    assert "GL002" in rules_of(out)


def test_unmarked_module_not_gated():
    out = lint("""
        import jax
    """, relpath="some/new_module.py")
    assert "GL002" not in rules_of(out)


def test_own_false_marker_overrides_discovered_set():
    # predict_fast.py is marker-discovered jax-free in the real tree;
    # an explicit False declaration in the source under lint wins
    out = lint("""
        __jax_free__ = False
        import jax
    """, relpath="predict_fast.py")
    assert "GL002" not in rules_of(out)


def test_discovered_set_covers_real_modules():
    out = lint("""
        import jax
    """, relpath="predict_fast.py")
    assert "GL002" in rules_of(out)


def test_import_of_non_jax_free_module_still_flagged():
    out = lint("""
        __jax_free__ = True
        from .models.gbdt import GBDT
    """, relpath="somemod.py")
    assert "GL002" in rules_of(out)


# ---------------------------------------------------------------------------
# GL006 x @contract.locked_by: the obligation moves to graftcheck GC004
# ---------------------------------------------------------------------------

def test_locked_by_contract_exempts_gl006():
    out = lint("""
        from ..analysis.contracts import contract

        class Hist:
            @contract.locked_by("_lock")
            def observe(self, v):
                self.total += v
    """, relpath="serving/metrics_like.py")
    assert "GL006" not in rules_of(out)


def test_uncontracted_serving_store_still_flagged():
    out = lint("""
        class Hist:
            def observe(self, v):
                self.total += v
    """, relpath="serving/metrics_like.py")
    assert "GL006" in rules_of(out)


# ---------------------------------------------------------------------------
# suppression binding across decorators
# ---------------------------------------------------------------------------

def test_suppression_above_decorator_binds_to_def():
    out = lint("""
        import jax

        # graftlint: disable=GL004 -- test fixture retraces per mode on
        # purpose; two modes total, bounded by the driver
        @jax.jit
        def f(x, mode: str = "a"):
            return x
    """)
    assert "GL004" not in rules_of(out)
    assert "GL010" not in rules_of(out)  # and the suppression is not stale


def test_suppression_above_multiline_decorator_binds_to_def():
    out = lint("""
        import functools
        import jax

        # graftlint: disable=GL004 -- test fixture retraces per mode on
        # purpose; two modes total, bounded by the driver
        @functools.partial(jax.jit,
                           donate_argnums=(0,))
        def f(x, mode: str = "a"):
            return x
    """)
    assert "GL004" not in rules_of(out)


def test_suppression_on_decorated_def_without_comment_still_fires():
    out = lint("""
        import jax

        @jax.jit
        def f(x, mode: str = "a"):
            return x
    """)
    assert "GL004" in rules_of(out)


def test_marker_inside_docstring_does_not_count():
    # a column-0 example line inside a docstring is TEXT, not a
    # declaration (the marker is read from the AST, not by regex)
    out = lint('''
        """Example of the convention:

        __jax_free__ = True
        """
        import jax
    ''', relpath="some/new_module.py")
    assert "GL002" not in rules_of(out)


def test_type_checking_else_branch_still_gated():
    # `if TYPE_CHECKING: ... else: import jax` imports jax in every
    # REAL process — the else branch must not ride the guard's exemption
    out = lint("""
        __jax_free__ = True
        from typing import TYPE_CHECKING

        if TYPE_CHECKING:
            import jax
        else:
            import jax
    """, relpath="some/new_module.py")
    assert "GL002" in rules_of(out)
