"""Unit tests for the fault-tolerance subsystem (lightgbm_tpu/resilience/):
atomic durable writes, deterministic fault injection, hardened network
helpers, the snapshot manager's cadence/validation/resume policies, and
the corrupt-binary-cache fallback regression.

Chaos round-trips (SIGKILL + resume=auto byte identity) live in
test_chaos.py; serving failure paths in test_serving_resilience.py.
"""

import io
import os
import threading
import time

import numpy as np
import pytest

from lightgbm_tpu.resilience import atomic
from lightgbm_tpu.resilience import faults
from lightgbm_tpu.resilience import net
from lightgbm_tpu.resilience.snapshot import (SnapshotManager,
                                              snapshot_name,
                                              validate_snapshot)


@pytest.fixture(autouse=True)
def _clean_fault_registry():
    faults.reset()
    yield
    faults.reset()


# ---------------------------------------------------------------------------
# atomic: crash-safe writes + integrity footer
# ---------------------------------------------------------------------------

class TestAtomic:
    def test_write_read_roundtrip_with_footer(self, tmp_path):
        p = str(tmp_path / "a.bin")
        atomic.atomic_write_bytes(p, b"payload-bytes")
        assert atomic.read_verified(p) == b"payload-bytes"
        assert atomic.verify_file(p) == "ok"
        # the footer is 40 bytes past the payload on disk
        assert os.path.getsize(p) == len(b"payload-bytes") + atomic.FOOTER_LEN

    @staticmethod
    def _dead_pid():
        """A pid that provably belonged to a dead process."""
        import subprocess
        import sys
        proc = subprocess.Popen([sys.executable, "-c", "pass"])
        proc.wait()
        return proc.pid

    @staticmethod
    def _make_stale(path):
        """Age a tmp past the sweep's quiet threshold."""
        old = time.time() - atomic.STALE_TMP_S - 60
        os.utime(path, (old, old))

    def test_stale_tmp_swept_on_next_write(self, tmp_path):
        # a SIGKILL mid-write orphans a pid-tagged tmp; the NEXT writer
        # for the same target (a fresh pid after resume) must sweep it,
        # while leaving other targets' tmps and non-tmp siblings alone
        p = str(tmp_path / "model.txt")
        dead_pid = self._dead_pid()
        stale = "%s.%d.lgtmp" % (p, dead_pid)
        other = "%s.%d.lgtmp" % (str(tmp_path / "other.txt"), dead_pid)
        lookalike = p + ".notapid.lgtmp"
        for f in (stale, other, lookalike):
            with open(f, "wb") as fh:
                fh.write(b"orphan")
            self._make_stale(f)
        atomic.atomic_write_bytes(p, b"fresh")
        assert not os.path.exists(stale)
        assert os.path.exists(other) and os.path.exists(lookalike)
        assert atomic.read_verified(p) == b"fresh"

    def test_stale_tmp_swept_by_text_writer(self, tmp_path):
        p = str(tmp_path / "model.txt")
        stale = "%s.%d.lgtmp" % (p, self._dead_pid())
        with open(stale, "wb") as fh:
            fh.write(b"orphan")
        self._make_stale(stale)
        w = atomic.text_writer(p)
        w.write("t\n")
        w.close()
        assert not os.path.exists(stale)
        assert open(p).read() == "t\n"

    def test_live_writer_tmp_never_swept(self, tmp_path):
        # multi-host ranks may write the SAME target concurrently on a
        # shared filesystem: a foreign tmp is reaped only when its
        # writer is provably dead on this host AND it has gone quiet —
        # a fresh mtime (live local writer or unprobeable cross-host
        # writer) or a live pid must both protect it
        p = str(tmp_path / "model.txt")
        live_fresh = "%s.%d.lgtmp" % (p, self._dead_pid())
        with open(live_fresh, "wb") as fh:
            fh.write(b"mid-write")           # fresh mtime: still active
        live_pid = "%s.%d.lgtmp" % (p, os.getppid())
        with open(live_pid, "wb") as fh:
            fh.write(b"mid-write")
        self._make_stale(live_pid)           # stale but pid is alive
        atomic.atomic_write_bytes(p, b"fresh")
        assert os.path.exists(live_fresh)
        assert os.path.exists(live_pid)

    def test_footerless_file_is_legacy(self, tmp_path):
        p = str(tmp_path / "legacy.bin")
        with open(p, "wb") as f:
            f.write(b"old-format")
        assert atomic.verify_file(p) == "legacy"
        assert atomic.read_verified(p) == b"old-format"

    def test_bit_flip_detected(self, tmp_path):
        p = str(tmp_path / "a.bin")
        atomic.atomic_write_bytes(p, b"x" * 100)
        raw = bytearray(open(p, "rb").read())
        raw[50] ^= 0x40
        with open(p, "wb") as f:
            f.write(raw)
        assert atomic.verify_file(p).startswith("corrupt")
        with pytest.raises(atomic.IntegrityError):
            atomic.read_verified(p)

    def test_zero_length_is_corrupt(self, tmp_path):
        p = str(tmp_path / "z.bin")
        open(p, "wb").close()
        assert atomic.verify_file(p) == "corrupt: zero-length file"

    def test_missing_file_is_corrupt_not_raise(self, tmp_path):
        assert atomic.verify_file(str(tmp_path / "nope")).startswith(
            "corrupt: unreadable")

    def test_failed_write_leaves_previous_file_and_no_tmp(self, tmp_path):
        p = str(tmp_path / "a.bin")
        atomic.atomic_write_bytes(p, b"GOOD")
        with pytest.raises(RuntimeError, match="mid-write"):
            with atomic.atomic_writer(p) as f:
                f.write(b"PARTIAL")
                raise RuntimeError("mid-write crash")
        assert atomic.read_verified(p) == b"GOOD"
        assert [n for n in os.listdir(tmp_path)] == ["a.bin"]

    def test_streaming_checksum_matches_one_shot(self, tmp_path):
        a, b = str(tmp_path / "a"), str(tmp_path / "b")
        atomic.atomic_write_bytes(a, b"abcdef" * 1000)
        with atomic.atomic_writer(b, checksum=True) as f:
            for _ in range(1000):
                f.write(b"abcdef")
        assert open(a, "rb").read() == open(b, "rb").read()

    def test_text_writer_commit_and_abort(self, tmp_path):
        p = str(tmp_path / "m.txt")
        w = atomic.text_writer(p)
        w.write("tree\n")
        assert not os.path.exists(p)      # nothing visible until commit
        w.close()
        assert open(p).read() == "tree\n"
        w2 = atomic.text_writer(p)
        w2.write("GARBAGE")
        w2.abort()
        assert open(p).read() == "tree\n"  # abort never touches the file
        assert os.listdir(tmp_path) == ["m.txt"]

    def test_npz_roundtrip_keeps_exact_path(self, tmp_path):
        p = str(tmp_path / "snap.lgts")    # no .npz suffix on purpose
        atomic.write_npz(p, {"iter": np.int64(3),
                             "v": np.arange(5.0)})
        assert os.path.exists(p)
        with atomic.read_npz(p) as z:
            assert int(z["iter"]) == 3
            np.testing.assert_array_equal(z["v"], np.arange(5.0))

    def test_corrupt_npz_raises_integrity_error(self, tmp_path):
        p = str(tmp_path / "snap.lgts")
        atomic.write_npz(p, {"iter": np.int64(3)})
        raw = bytearray(open(p, "rb").read())
        raw[10] ^= 0xFF
        with open(p, "wb") as f:
            f.write(raw)
        with pytest.raises(atomic.IntegrityError):
            atomic.read_npz(p)


# ---------------------------------------------------------------------------
# faults: deterministic, seeded injection
# ---------------------------------------------------------------------------

class TestFaults:
    def test_unknown_faultpoint_rejected(self):
        with pytest.raises(ValueError, match="unknown faultpoint"):
            faults.configure("no.such.seam@1=raise")

    def test_unknown_action_rejected(self):
        with pytest.raises(ValueError, match="unknown fault action"):
            faults.configure("dist.send@1=explode")

    def test_exact_hit_fires_once(self):
        faults.configure("dist.send@3=raise:boom")
        faults.faultpoint("dist.send")
        faults.faultpoint("dist.send")
        with pytest.raises(faults.FaultInjected, match="boom"):
            faults.faultpoint("dist.send")
        faults.faultpoint("dist.send")     # hit 4: rule is exact, no fire
        assert faults.hits("dist.send") == 4
        assert faults.fired("dist.send") == 1

    def test_sticky_fires_from_hit_on(self):
        faults.configure("dist.recv@2+=raise")
        faults.faultpoint("dist.recv")
        for _ in range(3):
            with pytest.raises(faults.FaultInjected):
                faults.faultpoint("dist.recv")
        assert faults.fired("dist.recv") == 3

    def test_permille_schedule_is_seed_deterministic(self):
        def firing_hits(spec):
            faults.configure(spec)
            out = []
            for i in range(200):
                try:
                    faults.faultpoint("serve.dispatch")
                except faults.FaultInjected:
                    out.append(i)
            return out

        a = firing_hits("seed=7;serve.dispatch%100=raise")
        b = firing_hits("seed=7;serve.dispatch%100=raise")
        c = firing_hits("seed=8;serve.dispatch%100=raise")
        assert a == b and a                 # reproducible and non-empty
        assert a != c                       # and actually seed-driven

    def test_unarmed_faultpoint_is_noop(self):
        faults.faultpoint("reload.parse")
        assert faults.hits("reload.parse") == 1
        assert faults.fired("reload.parse") == 0

    def test_env_schedule_picked_up(self, monkeypatch):
        monkeypatch.setenv(faults.ENV_VAR, "reload.parse@1=raise:from-env")
        faults.reset()
        faults._REG._env_checked = False    # simulate fresh process
        with pytest.raises(faults.FaultInjected, match="from-env"):
            faults.faultpoint("reload.parse")

    def test_every_known_faultpoint_parses_in_a_schedule(self):
        spec = ";".join("%s@1000000=raise" % n
                        for n in faults.KNOWN_FAULTPOINTS)
        faults.configure(spec)              # closed registry accepts all


# ---------------------------------------------------------------------------
# net: bounded retries, bounded waits, typed errors
# ---------------------------------------------------------------------------

class TestNet:
    def test_connect_retry_succeeds_after_failures(self):
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise ConnectionRefusedError("not up yet")
            return "linked"

        got = net.connect_with_retry(flaky, "test-connect",
                                     deadline_s=30.0,
                                     base_delay_s=0.01, max_delay_s=0.02)
        assert got == "linked" and calls["n"] == 3

    def test_connect_retry_deadline_raises_typed_error(self):
        def always_down():
            raise ConnectionRefusedError("dead coordinator")

        t0 = time.monotonic()
        with pytest.raises(net.NetworkError,
                           match="dead coordinator") as ei:
            net.connect_with_retry(always_down, "test-connect",
                                   deadline_s=0.2, base_delay_s=0.05,
                                   max_delay_s=0.1)
        assert time.monotonic() - t0 < 5.0
        assert isinstance(ei.value.__cause__, ConnectionRefusedError)

    def test_connect_faultpoint_drives_attempts(self):
        faults.configure("dist.connect@1=raise:injected-refuse")
        got = net.connect_with_retry(lambda: "up", "test-connect",
                                     deadline_s=30.0,
                                     base_delay_s=0.01)
        assert got == "up"                 # attempt 2 passes
        assert faults.hits("dist.connect") == 2

    def test_deadline_passthrough_and_timeout(self):
        assert net.call_with_deadline(lambda: 41 + 1, 5.0, "quick") == 42
        assert net.call_with_deadline(lambda: "no-deadline", 0, "x") \
            == "no-deadline"
        ev = threading.Event()
        with pytest.raises(net.NetworkError, match="did not complete"):
            net.call_with_deadline(lambda: ev.wait(30), 0.1, "dead-peer")
        ev.set()

    def test_deadline_propagates_callee_error(self):
        def bad():
            raise KeyError("inner")

        with pytest.raises(KeyError, match="inner"):
            net.call_with_deadline(bad, 5.0, "x")


# ---------------------------------------------------------------------------
# snapshot manager: cadence, validation, resume
# ---------------------------------------------------------------------------

class _FakeBooster:
    """Minimal save/load_checkpoint carrier for manager-level tests."""

    def __init__(self, iteration=0):
        self.iter = iteration
        self.loaded_from = None

    def save_checkpoint(self, path):
        atomic.write_npz(path, {"iter": np.int64(self.iter),
                                "num_trees": np.int64(self.iter),
                                "scores": np.zeros(4)})

    def load_checkpoint(self, path):
        with atomic.read_npz(path) as z:
            self.iter = int(z["iter"])
        self.loaded_from = path


def _mgr(tmp_path, period=5, resume="auto", keep=0):
    return SnapshotManager(str(tmp_path), period, resume, keep=keep)


class TestSnapshotManager:
    def test_due_crosses_period_boundaries(self, tmp_path):
        m = _mgr(tmp_path, period=5)
        assert not m.due(4)
        assert m.due(5)
        assert m.due(12)                  # segments may jump boundaries
        m._last = 5
        assert not m.due(9)
        assert m.due(10)

    def test_period_zero_never_due(self, tmp_path):
        m = _mgr(tmp_path, period=0)
        assert not m.due(10 ** 9)

    def test_write_validate_resume_roundtrip(self, tmp_path):
        m = _mgr(tmp_path, period=5)
        m.write(_FakeBooster(5))
        m.write(_FakeBooster(10))
        assert validate_snapshot(
            os.path.join(str(tmp_path), snapshot_name(10))) is None
        b = _FakeBooster()
        assert m.maybe_resume(b) == 10
        assert b.iter == 10

    def test_resume_off_ignores_snapshots(self, tmp_path):
        m = _mgr(tmp_path, resume="off")
        m.write(_FakeBooster(5))
        b = _FakeBooster()
        assert _mgr(tmp_path, resume="off").maybe_resume(b) == 0
        assert b.iter == 0

    def test_resume_auto_empty_dir_starts_fresh(self, tmp_path):
        assert _mgr(tmp_path).maybe_resume(_FakeBooster()) == 0

    def test_resume_explicit_path(self, tmp_path):
        m = _mgr(tmp_path, period=5)
        m.write(_FakeBooster(5))
        path = os.path.join(str(tmp_path), snapshot_name(5))
        b = _FakeBooster()
        assert SnapshotManager(str(tmp_path), 0, path).maybe_resume(b) == 5
        assert b.loaded_from == path

    def test_resume_explicit_corrupt_path_fatals(self, tmp_path):
        from lightgbm_tpu.utils import log
        p = str(tmp_path / "bad.lgts")
        open(p, "wb").close()
        with pytest.raises(log.LightGBMError, match="rejected"):
            SnapshotManager(str(tmp_path), 0, p).maybe_resume(
                _FakeBooster())

    def test_resume_explicit_other_ranks_snapshot_fatals_multihost(
            self, tmp_path):
        # a shared conf naming rank 0's snapshot passes _agree's
        # iteration check on every rank while loading rank 0's SHARD
        # state everywhere — the silent SPMD divergence must abort
        # before any collective runs
        from lightgbm_tpu.utils import log
        SnapshotManager(str(tmp_path), 5, "auto").write(_FakeBooster(5))
        path = os.path.join(str(tmp_path), snapshot_name(5, rank=0))
        mgr = SnapshotManager(str(tmp_path), 0, path, rank=1,
                              num_machines=2)
        with pytest.raises(log.LightGBMError, match="ITS OWN"):
            mgr.maybe_resume(_FakeBooster())

    @pytest.mark.parametrize("damage", ["truncate", "bitflip", "zero"])
    def test_resume_auto_skips_corrupt_newest(self, tmp_path, damage,
                                              capsys):
        m = _mgr(tmp_path, period=5)
        m.write(_FakeBooster(5))
        m.write(_FakeBooster(10))
        newest = os.path.join(str(tmp_path), snapshot_name(10))
        raw = open(newest, "rb").read()
        if damage == "truncate":
            payload = raw[:len(raw) // 2]
        elif damage == "bitflip":
            payload = bytearray(raw)
            payload[len(raw) // 2] ^= 0x01
        else:
            payload = b""
        with open(newest, "wb") as f:
            f.write(payload)
        b = _FakeBooster()
        assert m.maybe_resume(b) == 5, damage
        assert b.iter == 5
        out = capsys.readouterr().out
        assert "Skipping snapshot" in out and snapshot_name(10) in out
        assert "corrupt" in out            # the reason is named

    def test_missing_required_keys_rejected(self, tmp_path):
        p = str(tmp_path / snapshot_name(5))
        atomic.write_npz(p, {"iter": np.int64(5)})
        reason = validate_snapshot(p)
        assert reason is not None and "missing key" in reason

    def test_fingerprint_mismatch_is_stale(self, tmp_path):
        # a snapshot written under a different config/dataset must be
        # rejected as stale — shape-coincident state would otherwise
        # silently continue the OLD run under the NEW config
        fp = "num_leaves=31;learning_rate=0.1"
        p = str(tmp_path / snapshot_name(5))
        atomic.write_npz(p, {"iter": np.int64(5),
                             "num_trees": np.int64(5),
                             "scores": np.zeros(4),
                             "resume_fp": np.array(fp)})
        assert validate_snapshot(p, expect_fp=fp) is None
        reason = validate_snapshot(
            p, expect_fp="num_leaves=63;learning_rate=0.1")
        assert reason is not None and reason.startswith("stale")
        assert "num_leaves" in reason          # the moved key is named
        assert "learning_rate" not in reason   # unchanged keys are not
        # pre-fingerprint snapshots stay loadable (legacy)
        q = str(tmp_path / snapshot_name(6))
        atomic.write_npz(q, {"iter": np.int64(6),
                             "num_trees": np.int64(6),
                             "scores": np.zeros(4)})
        assert validate_snapshot(q, expect_fp=fp) is None

    def test_resume_auto_skips_stale_fingerprint(self, tmp_path, capsys):
        from lightgbm_tpu.resilience.snapshot import resume_fingerprint

        class _CfgBooster(_FakeBooster):
            def __init__(self, iteration=0, leaves=31):
                super().__init__(iteration)
                self.config = type("C", (), {"num_leaves": leaves})()

            def save_checkpoint(self, path):
                atomic.write_npz(path, {
                    "iter": np.int64(self.iter),
                    "num_trees": np.int64(self.iter),
                    "scores": np.zeros(4),
                    "resume_fp": np.array(resume_fingerprint(self))})

        m = _mgr(tmp_path, period=5)
        m.write(_CfgBooster(5, leaves=31))
        b = _CfgBooster(leaves=63)
        assert m.maybe_resume(b) == 0          # stale skipped: fresh
        out = capsys.readouterr().out
        assert "Skipping snapshot" in out and "stale" in out
        same = _CfgBooster(leaves=31)
        assert m.maybe_resume(same) == 5       # matching config resumes

    def test_truncated_archive_without_footer_rejected(self, tmp_path):
        # legacy (footer-less) snapshot truncated mid-zip: the archive
        # check must catch what the checksum cannot
        buf = io.BytesIO()
        np.savez(buf, iter=np.int64(5), num_trees=np.int64(5),
                 scores=np.zeros(4))
        p = str(tmp_path / snapshot_name(5))
        with open(p, "wb") as f:
            f.write(buf.getvalue()[:60])
        reason = validate_snapshot(p)
        assert reason is not None and "corrupt" in reason

    def test_resume_never_exceeds_iteration_cap(self, tmp_path, capsys):
        # snapshots from a longer earlier run must not skip the loop
        # and hand back MORE iterations than this run asked for
        from lightgbm_tpu.utils import log
        w = _mgr(tmp_path, period=5)
        w.write(_FakeBooster(5))
        w.write(_FakeBooster(10))
        capped = SnapshotManager(str(tmp_path), 5, "auto",
                                 max_iteration=7)
        b = _FakeBooster()
        assert capped.maybe_resume(b) == 5
        out = capsys.readouterr().out
        assert "beyond this run's num_iterations" in out
        path10 = os.path.join(str(tmp_path), snapshot_name(10))
        with pytest.raises(log.LightGBMError, match="beyond"):
            SnapshotManager(str(tmp_path), 0, path10,
                            max_iteration=7).maybe_resume(_FakeBooster())
        # exactly AT the cap resumes (the run is simply complete)
        assert SnapshotManager(str(tmp_path), 0, path10,
                               max_iteration=10).maybe_resume(
                                   _FakeBooster()) == 10

    def test_orphan_tmp_sweep_spares_live_writers(self, tmp_path):
        # the snapshot-dir sweep carries atomic's guard: reap only
        # provably-dead AND quiet writers of THIS rank — a second live
        # run sharing the snapshot_dir must not lose its mid-write tmp
        dead_stale = str(tmp_path / (snapshot_name(3) + ".%d.lgtmp"
                                     % TestAtomic._dead_pid()))
        dead_fresh = str(tmp_path / (snapshot_name(4) + ".%d.lgtmp"
                                     % TestAtomic._dead_pid()))
        live_stale = str(tmp_path / (snapshot_name(6) + ".%d.lgtmp"
                                     % os.getppid()))
        other_rank = str(tmp_path / (snapshot_name(3, rank=1)
                                     + ".%d.lgtmp"
                                     % TestAtomic._dead_pid()))
        for f in (dead_stale, dead_fresh, live_stale, other_rank):
            with open(f, "wb") as fh:
                fh.write(b"orphan")
        for f in (dead_stale, live_stale, other_rank):
            TestAtomic._make_stale(f)
        _mgr(tmp_path, period=5, keep=2).write(_FakeBooster(5))
        assert not os.path.exists(dead_stale)       # reaped
        assert os.path.exists(dead_fresh)           # still writing?
        assert os.path.exists(live_stale)           # writer alive
        assert os.path.exists(other_rank)           # not ours to touch

    def test_retention_prunes_oldest(self, tmp_path):
        m = _mgr(tmp_path, period=1, keep=2)
        for i in (1, 2, 3, 4):
            m.write(_FakeBooster(i))
        names = sorted(os.listdir(str(tmp_path)))
        assert names == [snapshot_name(3), snapshot_name(4)]

    def test_rank_files_are_disjoint(self, tmp_path):
        m0 = SnapshotManager(str(tmp_path), 5, "auto", rank=0)
        m1 = SnapshotManager(str(tmp_path), 5, "auto", rank=1)
        m0.write(_FakeBooster(5))
        m1.write(_FakeBooster(10))
        assert m0.valid_iters() == [5]
        assert m1.valid_iters() == [10]

    def test_from_config_validation(self):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.utils import log
        cfg = Config.from_params({"snapshot_period": "5",
                                  "snapshot_dir": "/tmp/x"})
        assert SnapshotManager.from_config(cfg) is not None
        off = Config.from_params({})
        assert SnapshotManager.from_config(off) is None
        with pytest.raises(log.LightGBMError):
            Config.from_params({"snapshot_period": "5"})
        with pytest.raises(log.LightGBMError):
            Config.from_params({"resume": "auto"})


# ---------------------------------------------------------------------------
# corrupt binary-cache fallback (satellite regression)
# ---------------------------------------------------------------------------

def _tiny_tsv(tmp_path, n=120, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 4)
    y = (x[:, 0] > 0).astype(int)
    p = str(tmp_path / "train.tsv")
    with open(p, "w") as f:
        for i in range(n):
            f.write("%d\t" % y[i]
                    + "\t".join("%.6g" % v for v in x[i]) + "\n")
    return p


class TestCorruptCacheFallback:
    def _load(self, data, save=False):
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.io.dataset import load_dataset
        cfg = Config.from_params({
            "objective": "binary", "max_bin": 16,
            "is_save_binary_file": "true" if save else "false"})
        return load_dataset(data, cfg)

    def test_cache_has_integrity_footer(self, tmp_path):
        data = _tiny_tsv(tmp_path)
        self._load(data, save=True)
        assert atomic.verify_file(data + ".bin") == "ok"

    def test_corrupt_cache_falls_back_to_text(self, tmp_path, capsys):
        data = _tiny_tsv(tmp_path)
        want = self._load(data, save=True)
        # bit-flip INSIDE the payload: the section reader would parse
        # this "cleanly" into poisoned bins — only the checksum sees it
        cache = data + ".bin"
        raw = bytearray(open(cache, "rb").read())
        raw[len(raw) // 2] ^= 0x10
        with open(cache, "wb") as f:
            f.write(raw)
        got = self._load(data)
        out = capsys.readouterr().out
        assert "Failed to load binary cache" in out
        assert "sha256 mismatch" in out
        np.testing.assert_array_equal(np.asarray(got.bins),
                                      np.asarray(want.bins))

    def test_truncated_cache_falls_back_to_text(self, tmp_path, capsys):
        data = _tiny_tsv(tmp_path)
        want = self._load(data, save=True)
        cache = data + ".bin"
        raw = open(cache, "rb").read()
        with open(cache, "wb") as f:
            f.write(raw[:len(raw) // 3])
        got = self._load(data)
        assert "Failed to load binary cache" in capsys.readouterr().out
        np.testing.assert_array_equal(np.asarray(got.bins),
                                      np.asarray(want.bins))

    def test_corrupt_rows_sidecar_falls_back(self, tmp_path, capsys):
        """A corrupt `.rows.npz` partition sidecar must NOT silently
        desync the cluster's row sets: the rank-tagged cache is
        rejected and the partition re-derives from text."""
        from lightgbm_tpu import native
        if native.get_lib() is None:
            pytest.skip("native toolchain absent (shard lottery)")
        from lightgbm_tpu.config import Config
        from lightgbm_tpu.io.dataset import load_dataset
        data = _tiny_tsv(tmp_path, n=200)
        cfg_save = Config.from_params({
            "objective": "binary", "max_bin": 16,
            "is_save_binary_file": "true"})
        want = load_dataset(data, cfg_save, rank=0, num_shards=2)
        side = data + ".r0of2.bin.rows.npz"
        assert os.path.exists(side)
        raw = bytearray(open(side, "rb").read())
        raw[len(raw) // 2] ^= 0x08
        with open(side, "wb") as f:
            f.write(raw)
        capsys.readouterr()
        cfg = Config.from_params({"objective": "binary",
                                  "max_bin": 16})
        got = load_dataset(data, cfg, rank=0, num_shards=2)
        assert "Ignoring rank-tagged binary cache" \
            in capsys.readouterr().out
        np.testing.assert_array_equal(got.local_rows, want.local_rows)
        np.testing.assert_array_equal(np.asarray(got.bins),
                                      np.asarray(want.bins))

    def test_intact_cache_still_loads(self, tmp_path, capsys):
        data = _tiny_tsv(tmp_path)
        want = self._load(data, save=True)
        got = self._load(data)
        assert "Failed to load binary cache" not in capsys.readouterr().out
        np.testing.assert_array_equal(np.asarray(got.bins),
                                      np.asarray(want.bins))


# ---------------------------------------------------------------------------
# resilience/backoff.py — the ONE exponential-backoff curve
# ---------------------------------------------------------------------------

class TestBackoff:
    def test_deterministic_curve_and_cap(self):
        from lightgbm_tpu.resilience.backoff import Backoff
        b = Backoff(base_s=0.5, cap_s=8.0)
        assert [b.delay(i) for i in range(1, 7)] \
            == [0.5, 1.0, 2.0, 4.0, 8.0, 8.0]
        # raw counters below 1 clamp instead of exploding
        assert b.delay(0) == 0.5
        assert b.delay(-3) == 0.5
        # huge attempt numbers stay at the cap (no float overflow)
        assert b.delay(10_000) == 8.0

    def test_curve_matches_the_frontend_respawn_formula(self):
        """The respawn throttle's historical formula
        min(0.5 * 2**(n-1), 30.0) IS the shared curve — the dedup
        changed no delays."""
        from lightgbm_tpu.resilience.backoff import Backoff
        from lightgbm_tpu.serving.frontend import (
            RESPAWN_BACKOFF_S, RESPAWN_BACKOFF_MAX_S, _RESPAWN_CURVE)
        for n in range(1, 12):
            assert _RESPAWN_CURVE.delay(n) == min(
                RESPAWN_BACKOFF_S * (2 ** (n - 1)),
                RESPAWN_BACKOFF_MAX_S)
        assert _RESPAWN_CURVE.base_s == RESPAWN_BACKOFF_S
        assert _RESPAWN_CURVE.cap_s == RESPAWN_BACKOFF_MAX_S

    def test_seeded_jitter_is_reproducible(self):
        from lightgbm_tpu.resilience.backoff import Backoff
        a = Backoff(base_s=1.0, cap_s=16.0, jitter=0.5, seed=7)
        b = Backoff(base_s=1.0, cap_s=16.0, jitter=0.5, seed=7)
        da = [a.delay(i) for i in range(1, 8)]
        db = [b.delay(i) for i in range(1, 8)]
        assert da == db, "same seed must replay the same delays"
        plain = Backoff(base_s=1.0, cap_s=16.0)
        for n, d in enumerate(da, start=1):
            full = plain.delay(n)
            assert full * 0.5 <= d <= full, \
                "jitter=0.5 keeps a deterministic half floor"
        c = Backoff(base_s=1.0, cap_s=16.0, jitter=0.5, seed=8)
        assert [c.delay(i) for i in range(1, 8)] != da

    def test_invalid_parameters_rejected(self):
        from lightgbm_tpu.resilience.backoff import Backoff
        with pytest.raises(ValueError):
            Backoff(base_s=0.0)
        with pytest.raises(ValueError):
            Backoff(base_s=2.0, cap_s=1.0)
        with pytest.raises(ValueError):
            Backoff(factor=0.5)
        with pytest.raises(ValueError):
            Backoff(jitter=1.5)

    def test_retry_succeeds_after_failures(self):
        from lightgbm_tpu.resilience.backoff import retry_with_backoff
        calls = []
        sleeps = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        out = retry_with_backoff(flaky, "probe", deadline_s=60.0,
                                 base_s=0.25, cap_s=1.0,
                                 sleep=sleeps.append)
        assert out == "ok"
        assert len(calls) == 3
        assert sleeps == [0.25, 0.5]    # the curve, not wall clock

    def test_retry_deadline_chains_last_error(self):
        from lightgbm_tpu.resilience.backoff import (RetryDeadline,
                                                     retry_with_backoff)

        def always():
            raise ValueError("still broken")

        with pytest.raises(RetryDeadline) as ei:
            retry_with_backoff(always, "probe", deadline_s=0.0,
                               base_s=0.5, cap_s=1.0,
                               sleep=lambda s: None)
        assert isinstance(ei.value.__cause__, ValueError)
        assert "probe" in str(ei.value)

    def test_retry_give_up_on_propagates_immediately(self):
        from lightgbm_tpu.resilience.backoff import retry_with_backoff
        calls = []

        def injected():
            calls.append(1)
            raise faults.FaultInjected("chaos")

        with pytest.raises(faults.FaultInjected):
            retry_with_backoff(injected, "probe", deadline_s=60.0,
                               give_up_on=(faults.FaultInjected,),
                               sleep=lambda s: None)
        assert len(calls) == 1, \
            "an injected fault must not be retried away"

    def test_connect_with_retry_rides_the_shared_curve(self):
        """connect_with_retry after the dedup: same delays as before
        (0.5 doubling to the 8s cap), NetworkError at the deadline."""
        attempts = []

        def failing():
            attempts.append(time.monotonic())
            raise OSError("refused")

        t0 = time.monotonic()
        with pytest.raises(net.NetworkError):
            net.connect_with_retry(failing, "probe", deadline_s=1.5,
                                   base_delay_s=0.4, max_delay_s=0.8)
        elapsed = time.monotonic() - t0
        # attempt 1, sleep 0.4, attempt 2, sleep 0.8, attempt 3 -> the
        # next 0.8s sleep would cross the 1.5s deadline
        assert len(attempts) == 3
        assert elapsed < 5.0
